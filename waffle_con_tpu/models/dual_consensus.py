"""Dual-consensus engine: finds the one *or two* best consensuses for a
set of reads (e.g. the two haplotypes of a diplotype).

Capability parity with ``/root/reference/src/dual_consensus.rs:52-1350``,
over the scorer seam: a search node carries one or two consensus branches;
non-dual nodes may *split* into dual nodes whenever two extension symbols
both gather enough votes, and each read's pair of wavefronts is pruned to
one side once their edit distances diverge beyond ``dual_max_ed_delta`` —
that emergent pruning is what assigns reads to haplotypes.
"""

from __future__ import annotations

import logging
import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.obs import audit as obs_audit
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs.instrument import FrontierSampler
from waffle_con_tpu.obs.report import run_reported_search as _reported_search
from waffle_con_tpu.models import checkpoint as ckpt_mod
from waffle_con_tpu.models.frontier import FrontierSpeculator, GangMember
from waffle_con_tpu.models.consensus import (
    PROGRESS_LOG_INTERVAL,
    RUN_SIM_CAP,
    Consensus,
    EngineError,
    _replay_consensus,
    accept_record,
    candidates_from_stats,
    replay_arena_history,
    replay_run_bookkeeping,
    requeue_arena_nodes,
    shift_offsets,
    check_invariant,
)
from waffle_con_tpu.ops.scorer import (
    WavefrontScorer,
    fast_paths,
    make_scorer,
)
from waffle_con_tpu.utils.pqueue import PQueueTracker, SetPriorityQueue

logger = logging.getLogger(__name__)


class DualConsensus:
    """A dual (or degenerate single) consensus result.

    ``is_consensus1[i]`` says whether input read ``i`` is assigned to
    ``consensus1``; ``scores1``/``scores2`` hold the per-read costs against
    each consensus, ``None`` where tracking was pruned.  Equality ignores
    the score vectors (parity with
    ``/root/reference/src/dual_consensus.rs:66-75``).
    """

    __slots__ = ("consensus1", "consensus2", "is_consensus1", "scores1", "scores2")

    def __init__(
        self,
        consensus1: Consensus,
        consensus2: Optional[Consensus],
        is_consensus1: List[bool],
        scores1: List[Optional[int]],
        scores2: List[Optional[int]],
    ) -> None:
        if len(is_consensus1) != len(scores1) or len(is_consensus1) != len(scores2):
            raise EngineError(
                "is_consensus1, scores1, and scores2 must all be the same length"
            )
        self.consensus1 = consensus1
        self.consensus2 = consensus2
        self.is_consensus1 = is_consensus1
        self.scores1 = scores1
        self.scores2 = scores2

    def is_dual(self) -> bool:
        return self.consensus2 is not None

    def __eq__(self, rhs) -> bool:
        return (
            isinstance(rhs, DualConsensus)
            and self.consensus1 == rhs.consensus1
            and self.consensus2 == rhs.consensus2
            and self.is_consensus1 == rhs.is_consensus1
        )

    def __repr__(self) -> str:
        return (
            f"DualConsensus(consensus1={self.consensus1!r}, "
            f"consensus2={self.consensus2!r}, is_consensus1={self.is_consensus1})"
        )


def _extend_active_tables(
    cfg, activate_points, total_active_count, active_min_count, length
) -> None:
    """Grow the per-length active-read-count / dynamic-min-count tables by
    one entry when ``length`` is their current frontier.  The ONE copy of
    this arithmetic: the pop loop, the run-replay path, and the arena
    replay must stay bit-identical for the fast paths to match the
    per-symbol flow."""
    if len(active_min_count) == length + 1:
        new_total = total_active_count[length] + len(
            activate_points.get(length, [])
        )
        total_active_count.append(new_total)
        active_min_count.append(
            max(cfg.min_count, math.ceil(cfg.min_af * new_total))
        )



def build_dual_record(
    cost, n, fin1, fin2, act1, act2, cons1, cons2, is_dual
):
    """THE copy of the finalized-result arithmetic (reference
    ``/root/reference/src/dual_consensus.rs:438-492`` semantics): per
    read the better finalized side (ties side 1), lexicographic swap,
    grouped + full score vectors.  Shared by ``_finalize`` (live scorer
    fins) and the run-record replay (kernel-buffered fins) so the two
    can never drift.  Returns ``(result, total, counts1, counts2)``;
    raises for a read inactive on every tracked side."""
    indices = []
    best_scores = []
    for r in range(n):
        s1 = cost.apply(int(fin1[r])) if act1[r] else None
        s2 = cost.apply(int(fin2[r])) if is_dual and act2[r] else None
        if s1 is None and s2 is None:
            raise EngineError(
                "Finalize called on DWFA that was never initialized."
            )
        if s1 is not None and (s2 is None or s1 <= s2):
            indices.append(0)
            best_scores.append(s1)
        else:
            indices.append(1)
            best_scores.append(s2)
    swap = is_dual and cons2 < cons1
    is_consensus1 = [(idx == 0) ^ swap for idx in indices]
    grouped: List[List[int]] = [[], []]
    for idx, score in zip(indices, best_scores):
        grouped[idx].append(score)
    c1 = Consensus(cons1, cost, grouped[0])
    c2 = Consensus(cons2, cost, grouped[1])
    full1 = [cost.apply(int(fin1[r])) if act1[r] else None for r in range(n)]
    full2 = [
        cost.apply(int(fin2[r])) if is_dual and act2[r] else None
        for r in range(n)
    ]
    if swap:
        result = DualConsensus(c2, c1, is_consensus1, full2, full1)
    else:
        result = DualConsensus(
            c1, c2 if is_dual else None, is_consensus1, full1, full2
        )
    counts1 = sum(is_consensus1)
    return result, sum(best_scores), counts1, n - counts1


class _DualNode:
    """Search node holding one (non-dual) or two consensus branches."""

    __slots__ = (
        "is_dual",
        "lock1",
        "lock2",
        "consensus1",
        "consensus2",
        "h1",
        "h2",
        "active1",
        "active2",
        "offsets1",
        "offsets2",
        "stats1",
        "stats2",
        "prefetch",
    )

    def __init__(self):
        self.is_dual = False
        self.lock1 = False
        self.lock2 = False
        self.consensus1 = b""
        self.consensus2 = b""
        self.h1 = None
        self.h2 = None
        self.active1: List[bool] = []
        self.active2: List[bool] = []
        self.offsets1: List[Optional[int]] = []
        self.offsets2: List[Optional[int]] = []
        self.stats1 = None
        self.stats2 = None
        #: speculative expansion cache: ``(specs, children)`` built by a
        #: fused multi-node dispatch before this node was popped (pure
        #: cache — specs are a deterministic function of the stats)
        self.prefetch = None

    # -- identity ------------------------------------------------------
    def key(self) -> Tuple:
        return (
            self.is_dual,
            self.lock1,
            self.lock2,
            self.consensus1,
            self.consensus2,
            tuple(o if a else None for a, o in zip(self.active1, self.offsets1)),
            tuple(o if a else None for a, o in zip(self.active2, self.offsets2)),
        )

    def max_consensus_length(self) -> int:
        return max(len(self.consensus1), len(self.consensus2))

    # -- scoring -------------------------------------------------------
    def best_costs(self, cost: ConsensusCost) -> Tuple[List[int], List[int]]:
        """Per read, the best (index, score) over the tracked sides; ties
        go to side 0; untracked reads report index ``-1`` / score 0."""
        n = len(self.active1)
        indices = [-1] * n
        scores = [0] * n
        for r in range(n):
            best_score = None
            best_index = -1
            if self.active1[r]:
                best_score = cost.apply(int(self.stats1.eds[r]))
                best_index = 0
            if self.is_dual and self.active2[r]:
                s2 = cost.apply(int(self.stats2.eds[r]))
                if best_score is None or s2 < best_score:
                    best_score = s2
                    best_index = 1
            if best_score is not None:
                indices[r] = best_index
                scores[r] = best_score
        return indices, scores

    def total_cost(self, cost: ConsensusCost) -> int:
        _, scores = self.best_costs(cost)
        return sum(scores)

    def priority(self, cost: ConsensusCost) -> Tuple[int, int]:
        return (-self.total_cost(cost), self.max_consensus_length())

    # -- predicates ------------------------------------------------------
    def is_dual_imbalanced(self, min_count: int) -> bool:
        if not self.is_dual:
            return False
        return sum(self.active1) < min_count or sum(self.active2) < min_count

    def reached_all_end(self, require_all: bool) -> bool:
        flags = []
        for r in range(len(self.active1)):
            p1 = self.active1[r] and bool(self.stats1.reached[r])
            p2 = (
                self.is_dual
                and self.active2[r]
                and bool(self.stats2.reached[r])
            )
            flags.append(p1 or p2)
        return all(flags) if require_all else any(flags)

    def reached_consensus_end(self, side1: bool, require_all: bool) -> bool:
        if not side1 and not self.is_dual:
            return False
        active = self.active1 if side1 else self.active2
        stats = self.stats1 if side1 else self.stats2
        flags = [
            bool(stats.reached[r]) if active[r] else require_all
            for r in range(len(active))
        ]
        return all(flags) if require_all else any(flags)

    # -- votes -----------------------------------------------------------
    def ed_weights(self, side1: bool, weight_by_ed: bool) -> List[float]:
        """Per-read vote weights from the relative edit distances of the
        two tracked sides (``/root/reference/src/dual_consensus.rs:1299-1336``)."""
        n = len(self.active1)
        if not self.is_dual:
            return [1.0] * n
        min_ed = 0.5
        equality_score = 0.5
        out = []
        for r in range(n):
            c1 = max(float(self.stats1.eds[r]), min_ed) if self.active1[r] else None
            c2 = max(float(self.stats2.eds[r]), min_ed) if self.active2[r] else None
            if c1 is not None and c2 is not None:
                if weight_by_ed:
                    numer = c2 if side1 else c1
                    out.append(numer / (c1 + c2))
                elif c1 == c2:
                    out.append(equality_score)
                elif (side1 and c1 < c2) or (not side1 and c2 < c1):
                    out.append(1.0)
                else:
                    out.append(0.0)
            elif (c1 is not None and side1) or (c2 is not None and not side1):
                out.append(1.0)
            else:
                out.append(0.0)
        return out

    def candidates(
        self, side1: bool, symtab, wildcard, weighted_by_ed: bool
    ) -> Dict[int, float]:
        active = self.active1 if side1 else self.active2
        stats = self.stats1 if side1 else self.stats2
        if weighted_by_ed:
            weights = self.ed_weights(side1, True)
        else:
            weights = [1.0] * len(active)
        # mask untracked reads: their stats rows may be stale
        weights = [w if a else 0.0 for w, a in zip(weights, active)]
        return candidates_from_stats(stats, symtab, wildcard, weights)


class DualConsensusDWFA:
    """Generates the best single- or dual-consensus for the added reads.

    Example::

        from waffle_con_tpu import DualConsensusDWFA

        engine = DualConsensusDWFA()
        for s in reads:
            engine.add_sequence(s)
        results = engine.consensus()
    """

    def __init__(
        self,
        config: Optional[CdwfaConfig] = None,
        scorer: Optional[WavefrontScorer] = None,
    ) -> None:
        self.config = config if config is not None else CdwfaConfig()
        self.sequences: List[bytes] = []
        self.offsets: List[Optional[int]] = []
        self.alphabet: set = set()
        #: optional injected scorer (e.g. a SubsetScorer view of a scorer
        #: shared across priority-engine worklist groups); its reads must
        #: match the added sequences exactly
        self._injected_scorer = scorer

    @classmethod
    def with_config(cls, config: CdwfaConfig) -> "DualConsensusDWFA":
        return cls(config)

    def add_sequence(self, sequence: bytes) -> None:
        self.add_sequence_offset(sequence, None)

    def add_sequence_offset(
        self, sequence: bytes, last_offset: Optional[int]
    ) -> None:
        sequence = bytes(sequence)
        self.alphabet.update(sequence)
        if self.config.wildcard is not None:
            self.alphabet.discard(self.config.wildcard)
        self.sequences.append(sequence)
        self.offsets.append(last_offset)

    @property
    def consensus_cost(self) -> ConsensusCost:
        return self.config.consensus_cost

    # ==================================================================

    def consensus(self) -> List[DualConsensus]:
        """Run the search; returns every tied-best result (sorted), or a
        single empty-consensus fallback when no candidate survives.

        Wraps :meth:`_consensus_impl` in a ``search`` tracer span and
        publishes the structured :class:`SearchReport` as
        ``self.last_search_report`` (one-line summary logged at INFO
        when ``config.log_search_summary`` is set, else DEBUG).
        """
        return _reported_search(self, "dual", self._consensus_impl)

    def _consensus_impl(self) -> List[DualConsensus]:
        """Parity skeleton: ``/root/reference/src/dual_consensus.rs:240-787``."""
        cfg = self.config
        cost = cfg.consensus_cost
        restore = getattr(self, "_restore_state", None)
        self._restore_state = None
        n_seqs = len(self.sequences)
        maximum_error = math.inf
        farthest_single = 0
        farthest_dual = 0
        single_last_constraint = 0
        dual_last_constraint = 0
        nodes_explored = 0
        nodes_ignored = 0
        peak_queue_size = 0

        offsets = shift_offsets(self.offsets, cfg.auto_shift_offsets)
        logger.debug("Offsets: %s", offsets)

        activate_points: Dict[int, List[int]] = {}
        initially_active = 0
        for seq_index, offset in enumerate(offsets):
            if offset is not None:
                activate_length = offset + cfg.offset_compare_length
                activate_points.setdefault(activate_length, []).append(seq_index)
            else:
                initially_active += 1
        if initially_active == 0:
            raise EngineError(
                "Must have at least one initial offset of None to see the consensus."
            )

        if self._injected_scorer is not None:
            scorer = self._injected_scorer
            check_invariant(
                scorer.reads == self.sequences,
                "injected scorer reads match added sequences",
            )
        else:
            scorer = make_scorer(self.sequences, cfg)
        # shared (injected) scorers carry cumulative counters across
        # groups; report this search's delta, not the running total
        counters_before = dict(getattr(scorer, "counters", {}))
        initial_size = max(len(s) for s in self.sequences)
        single_tracker = PQueueTracker(initial_size, cfg.max_capacity_per_size)
        dual_tracker = PQueueTracker(initial_size, cfg.max_capacity_per_size)
        pqueue = SetPriorityQueue()

        if restore is None:
            root = _DualNode()
            root.active1 = [o is None for o in offsets]
            root.active2 = [False] * n_seqs
            root.offsets1 = [0 if a else None for a in root.active1]
            root.offsets2 = [None] * n_seqs
            root.h1 = scorer.root(np.array(root.active1, dtype=bool))
            root.stats1 = scorer.stats(root.h1, b"")
            single_tracker.insert(root.max_consensus_length())
            pqueue.push(root.key(), root, root.priority(cost))

        results: List[DualConsensus] = []

        # dynamic minimum counts driven by how many reads are active
        full_min_count = max(
            cfg.min_count, math.ceil(cfg.min_af * n_seqs)
        )
        total_active_count = [initially_active]
        active_min_count = [
            max(cfg.min_count, math.ceil(cfg.min_af * initially_active))
        ]
        # device-table forms of the dynamic-min-count arithmetic: the
        # activation schedule is known up front, so the whole per-length
        # active_min_count table is precomputable in exact host integer
        # arithmetic and uploaded to the run/arena kernels — min_af != 0
        # keeps the device fast paths (VERDICT r4 weak #3;
        # /root/reference/src/dual_consensus.rs:326-336,497-513)
        mc_tab = np.array(
            [
                max(cfg.min_count, math.ceil(cfg.min_af * n))
                for n in range(n_seqs + 1)
            ],
            dtype=np.int32,
        )
        last_act = max(activate_points, default=0)
        imb_tab = np.empty(last_act + 2, dtype=np.int32)
        _tot = initially_active
        imb_tab[0] = max(cfg.min_count, math.ceil(cfg.min_af * _tot))
        for _L in range(last_act + 1):
            _tot += len(activate_points.get(_L, []))
            imb_tab[_L + 1] = max(
                cfg.min_count, math.ceil(cfg.min_af * _tot)
            )

        pops = 0
        if restore is not None:
            (maximum_error, farthest_single, farthest_dual,
             single_last_constraint, dual_last_constraint,
             nodes_explored, nodes_ignored, peak_queue_size, pops,
             results, total_active_count, active_min_count) = (
                self._restore_search(
                    restore, scorer, pqueue, single_tracker, dual_tracker,
                    cost, total_active_count, active_min_count,
                )
            )
        frontier = FrontierSampler("dual")
        speculator = FrontierSpeculator(scorer, cfg)
        #: decision audit sink (``None`` when WAFFLE_AUDIT is off — the
        #: zero-overhead decision, made once per search)
        audit = obs_audit.search_sink("dual")

        ctrl = ckpt_mod.current_controller()

        def _ckpt_body() -> Dict:
            # closure over the loop locals: reads their values at
            # snapshot time, always at the top-of-pop-loop boundary
            return self._checkpoint_body(
                pqueue, single_tracker, dual_tracker,
                maximum_error=maximum_error,
                farthest_single=farthest_single,
                farthest_dual=farthest_dual,
                single_last_constraint=single_last_constraint,
                dual_last_constraint=dual_last_constraint,
                nodes_explored=nodes_explored,
                nodes_ignored=nodes_ignored,
                peak_queue_size=peak_queue_size,
                pops=pops,
                results=results,
                total_active_count=total_active_count,
                active_min_count=active_min_count,
            )

        while not pqueue.is_empty():
            if ctrl is not None:
                try:
                    ctrl.poll(pops, _ckpt_body)
                finally:
                    self._last_checkpoint = ctrl.last_checkpoint
            peak_queue_size = max(peak_queue_size, len(pqueue))
            while (
                len(single_tracker) > cfg.max_queue_size
                or single_last_constraint >= cfg.max_nodes_wo_constraint
            ) and single_tracker.threshold() < farthest_single:
                single_tracker.increment_threshold()
                single_last_constraint = 0
            while (
                len(dual_tracker) > cfg.max_queue_size
                or dual_last_constraint >= cfg.max_nodes_wo_constraint
            ) and dual_tracker.threshold() < farthest_dual:
                dual_tracker.increment_threshold()
                dual_last_constraint = 0

            node, priority = pqueue.pop()
            pops += 1
            if pops % PROGRESS_LOG_INTERVAL == 0:
                logger.debug(
                    "search progress: %d pops, queue=%d, farthest=%d/%d, "
                    "best_cost=%d", pops, len(pqueue), farthest_single,
                    farthest_dual, -priority[0],
                )
                if obs_metrics.metrics_enabled():
                    obs_metrics.registry().gauge(
                        "waffle_search_queue_depth", engine="dual"
                    ).set(len(pqueue))
            next_prio = pqueue.peek_priority()
            # per-pop adaptive-width tick (pure policy, byte-safe): see
            # the single engine — keeps sampled gang_width honest and
            # ticks cooldowns in real pops
            gang_w = speculator.width(
                len(pqueue),
                (-next_prio[0]) - (-priority[0])
                if next_prio is not None else None,
            )
            if frontier.due(pops):
                frontier.sample(
                    pops, len(pqueue),
                    len(single_tracker) + len(dual_tracker),
                    -priority[0],
                    -next_prio[0] if next_prio is not None else None,
                    node.max_consensus_length(),
                    max(farthest_single, farthest_dual),
                    counters=getattr(scorer, "counters", None),
                    gang_width=gang_w,
                )
            top_cost = -priority[0]
            top_len = node.max_consensus_length()

            if node.is_dual:
                dual_tracker.remove(top_len)
                threshold_cutoff = dual_tracker.threshold()
                at_capacity = dual_tracker.at_capacity(top_len)
            else:
                single_tracker.remove(top_len)
                threshold_cutoff = single_tracker.threshold()
                at_capacity = single_tracker.at_capacity(top_len)

            if audit is not None:
                # node identity digests: host bytes/flags the engine
                # already owns (WL002: nothing new is fetched)
                a_cls = "d" if node.is_dual else "p"
                a_l1 = len(node.consensus1)
                a_l2 = len(node.consensus2) if node.is_dual else None
                a_d1 = obs_audit.crc_bytes(node.consensus1)
                a_d2 = (
                    obs_audit.crc_bytes(node.consensus2)
                    if node.is_dual else None
                )
                _acts = [[i for i, a in enumerate(node.active1) if a]]
                if node.is_dual:
                    _acts.append(
                        [i for i, a in enumerate(node.active2) if a]
                    )
                a_act = obs_audit.active_digest(*_acts)

            check_invariant(top_len < len(active_min_count), "active_min_count covers popped length")
            if (
                top_cost > maximum_error
                or top_len < threshold_cutoff
                or at_capacity
                or node.is_dual_imbalanced(active_min_count[top_len])
            ):
                nodes_ignored += 1
                if audit is not None:
                    audit.emit({
                        "kind": "ignored", "pop": pops, "cls": a_cls,
                        "l1": a_l1, "l2": a_l2, "d1": a_d1, "d2": a_d2,
                        "act": a_act, "prio": top_cost,
                    })
                self._free_node(scorer, node)
                continue

            # -- device fast path: extend the popped node through
            # unambiguous stretches on device (dual nodes step BOTH
            # branches per iteration with on-device divergence pruning).
            # Engages only when this pop's own child spec is the single
            # both-sides-extend (or single-symbol) case, while the node
            # keeps winning pops (see models/consensus.py), with max_steps
            # bounded by the exact tracker simulation.  min_af != 0 rides
            # the precomputed mc/imb device tables; weighted_by_ed with
            # min_af != 0 makes vote totals fractional (the table index
            # would be meaningless), so only that combination falls back
            # to the per-symbol flow.  A locked side would stall the
            # max-length bookkeeping, so those fall back too.
            farthest_kind = farthest_dual if node.is_dual else farthest_single
            kind_tracker = dual_tracker if node.is_dual else single_tracker
            #: one-side-locked dual runs engage only while the unlocked
            #: side is at least as long as the locked one — the node's
            #: max length then advances one per committed step, so the
            #: tracker replay / run-bound simulation stay valid (in the
            #: brief opposite regime the per-symbol flow handles it)
            lockable = (
                not (node.lock1 and node.lock2)
                and (
                    not node.lock1
                    or len(node.consensus2) >= len(node.consensus1)
                )
                and (
                    not node.lock2
                    or len(node.consensus1) >= len(node.consensus2)
                )
            )
            #: both run kernels absorb reached-state records (buffered
            #: finalized snapshots replayed after the call), so reached
            #: nodes engage the plain runs; only the arena (no record
            #: support) skips them
            reached_now = node.reached_all_end(cfg.allow_early_termination)
            fp = fast_paths(scorer)
            kernels_ok = (
                cfg.min_af == 0.0 or not cfg.weighted_by_ed
            ) and (
                (
                    node.is_dual
                    and lockable
                    and fp.run_extend_dual is not None
                )
                or (
                    not node.is_dual
                    and fp.run_extend is not None
                )
            )
            runnable = False
            arena_shape = False
            cre_cap = fp.arena_cre_per_event

            def kernel_exact(nd):
                """Host mirror of the kernel's split-absorption vote
                safety: with ``min_af == 0`` the kernel also absorbs
                clear-margin fractional splits (``split_relax``), so
                only the weighted fold is categorically out; otherwise
                require every ACTIVE voting read single-tip (the
                kernel's ``exactable``).  Engaging the arena for a split
                the kernel must refuse would waste the dispatch."""
                if cfg.weighted_by_ed:
                    return False
                if cfg.min_af == 0.0:
                    return True
                wc_id = (
                    scorer.sym_id.get(cfg.wildcard)
                    if cfg.wildcard is not None
                    else None
                )
                for active, stats in (
                    (nd.active1, nd.stats1),
                    (nd.active2, nd.stats2) if nd.is_dual else (None, None),
                ):
                    if stats is None:
                        continue
                    split = stats.split
                    nondyadic = (split & (split - 1)) != 0
                    voting = np.asarray(active, dtype=bool) & (split > 0)
                    if (nondyadic & voting).any():
                        return False
                    # mixed wildcard/non-wildcard tips leave a fractional
                    # surviving-vote total after the wc drop — the
                    # kernel's integer mc-table index then refuses
                    # (tab_bad), so don't burn the dispatch
                    if wc_id is not None:
                        mixed = (
                            (stats.occ[:, wc_id] > 0)
                            & (stats.occ.sum(axis=1) > stats.occ[:, wc_id])
                        )
                        if (mixed & voting).any():
                            return False
                return True

            if kernels_ok:
                specs_now = (
                    node.prefetch[0]
                    if node.prefetch is not None
                    else self._build_specs(scorer, node)
                )
                if node.is_dual:
                    # the single-child spec: both sides extend, or the
                    # locked side contributes its forced None
                    runnable = (
                        len(specs_now) == 1
                        and specs_now[0][0] == "dual"
                        and (specs_now[0][1] is not None or node.lock1)
                        and (specs_now[0][2] is not None or node.lock2)
                        and (specs_now[0][1] is not None or specs_now[0][2] is not None)
                    )
                    # split-shaped: an all-extend cross product the arena
                    # can absorb as on-device children
                    arena_shape = runnable or (
                        2 <= len(specs_now) <= cre_cap
                        and all(
                            kind == "dual" and a is not None and b is not None
                            for kind, a, b in specs_now
                        )
                        and kernel_exact(node)
                    )
                else:
                    runnable = len(specs_now) == 1 and specs_now[0][0] == "single"
                    arena_shape = runnable or (
                        2 <= len(specs_now) <= cre_cap
                        and kernel_exact(node)
                    )
            # -- arena fast path: when the best OTHER queue entry is an
            # arena-compatible node, resolve the A<->B pop competition on
            # device (>99% of plain-run stops are "would lose the next
            # pop"); split-shaped expansions may engage too — the kernel
            # absorbs clean splits as on-device children and stops for
            # host arbitration otherwise.  Falls back to the single-node
            # run below when not engaged.
            if (
                arena_shape
                and not reached_now
                and not (node.is_dual and (node.lock1 or node.lock2))
                and fp.run_arena is not None
                # under lockstep shadow the arena's opaque subtree
                # absorption would hide per-pop decisions from the
                # comparator; strict alignment skips it (byte-safe:
                # the arena is a pure fast path)
                and not (audit is not None and audit.strict_align)
                # a pending frontier-gang deposit is this pop's run
                # already paid for; the arena would drop it unspent
                and not speculator.pending(node.h1)
            ):
                arena = self._arena_attempt(
                    scorer, pqueue, node, top_cost, maximum_error,
                    activate_points, cost, single_tracker, dual_tracker,
                    farthest_single, farthest_dual,
                    single_last_constraint, dual_last_constraint,
                    total_active_count, active_min_count,
                    mc_tab, imb_tab,
                )
                if arena is not None:
                    (farthest_single, farthest_dual,
                     single_last_constraint, dual_last_constraint,
                     arena_explored, arena_ignored) = arena
                    nodes_explored += arena_explored
                    nodes_ignored += arena_ignored
                    if audit is not None:
                        audit.emit({
                            "kind": "arena", "pop": pops, "cls": a_cls,
                            "l1": a_l1, "l2": a_l2, "d1": a_d1,
                            "d2": a_d2, "act": a_act, "prio": top_cost,
                            "explored": arena_explored,
                            "ignored": arena_ignored,
                        })
                    continue
            if runnable:
                best_other = pqueue.peek_priority()
                other_cost = 2**31 - 1
                other_len = 0
                if best_other is not None:
                    other_cost = -best_other[0]
                    other_len = best_other[1]
                if top_cost < other_cost or (
                    top_cost == other_cost and top_len > other_len
                ):
                    next_act = min(
                        (l for l in activate_points if l > top_len), default=None
                    )
                    max_steps = min(initial_size * 2 + 256, RUN_SIM_CAP)
                    if next_act is not None:
                        max_steps = min(max_steps, next_act - top_len - 1)
                    if max_steps >= 1:
                        max_steps = kind_tracker.simulate_run_bound(
                            top_len,
                            farthest_kind,
                            dual_last_constraint
                            if node.is_dual
                            else single_last_constraint,
                            cfg.max_queue_size,
                            cfg.max_nodes_wo_constraint,
                            max_steps,
                        )
                    if max_steps >= 1:
                        me_budget = (
                            int(maximum_error)
                            if maximum_error != math.inf
                            else 2**31 - 1
                        )
                        l2 = cost is ConsensusCost.L2_DISTANCE
                        # see the single engine: records are only valid
                        # under early termination when every read is
                        # already active on some tracked side
                        allow_recs = not cfg.allow_early_termination or all(
                            a1 or (node.is_dual and a2)
                            for a1, a2 in zip(node.active1, node.active2)
                        )
                        if node.is_dual:
                            (
                                steps,
                                _code,
                                app1,
                                app2,
                                stats1,
                                stats2,
                                act1,
                                act2,
                                dual_records,
                            ) = fp.run_extend_dual(
                                node.h1,
                                node.h2,
                                node.consensus1,
                                node.consensus2,
                                me_budget,
                                other_cost,
                                other_len,
                                cfg.min_count,
                                cfg.dual_max_ed_delta,
                                active_min_count[top_len],
                                l2,
                                cfg.weighted_by_ed,
                                max_steps,
                                lock1=node.lock1,
                                lock2=node.lock2,
                                allow_records=allow_recs,
                                rec_min=full_min_count,
                                mc_tab=mc_tab,
                                imb_tab=imb_tab,
                                mc_dyn=(cfg.min_af != 0.0),
                            )
                            # replay absorbed reached-state records in
                            # commit order — the exact _finalize +
                            # completion-path arithmetic, fed from the
                            # kernel's buffered snapshots
                            for rec_j, rf1, rf2, ra1, ra2 in dual_records:
                                try:
                                    (rec_result, rec_total, counts1,
                                     counts2) = build_dual_record(
                                        cost, n_seqs, rf1, rf2, ra1, ra2,
                                        node.consensus1 + app1[:rec_j],
                                        node.consensus2 + app2[:rec_j],
                                        True,
                                    )
                                except EngineError:
                                    self._free_node(scorer, node)
                                    raise
                                if (
                                    counts1 >= full_min_count
                                    and counts2 >= full_min_count
                                ):
                                    maximum_error = accept_record(
                                        maximum_error, results, rec_total,
                                        rec_result, cfg.max_return_size,
                                    )
                        else:
                            # frontier-parallel speculation over the
                            # non-dual branches of the frontier (dual
                            # nodes need the paired kernel, so only
                            # single-side members gang)
                            if gang_w > 1:
                                self._gang_attempt(
                                    speculator, scorer, pqueue, node,
                                    gang_w, me_budget, other_cost,
                                    other_len, max_steps, maximum_error,
                                    l2,
                                )
                            (steps, _code, app1, stats1,
                             run_records) = (
                                fp.run_mega
                                if fp.run_mega is not None
                                else fp.run_extend
                            )(
                                node.h1,
                                node.consensus1,
                                me_budget,
                                other_cost,
                                other_len,
                                cfg.min_count,
                                l2,
                                max_steps,
                                allow_records=allow_recs,
                            )
                            # replay absorbed reached-state records (the
                            # non-dual form of the completion path: no
                            # imbalance check, side 2 empty)
                            for rec_j, rec_fin in run_records:
                                try:
                                    (rec_result, rec_total, _c1,
                                     _c2) = build_dual_record(
                                        cost, n_seqs, rec_fin,
                                        np.zeros(n_seqs, dtype=np.int64),
                                        node.active1, node.active2,
                                        node.consensus1 + app1[:rec_j],
                                        node.consensus2, False,
                                    )
                                except EngineError:
                                    self._free_node(scorer, node)
                                    raise
                                maximum_error = accept_record(
                                    maximum_error, results, rec_total,
                                    rec_result, cfg.max_return_size,
                                )
                        if audit is not None and steps > 0:
                            audit.emit({
                                "kind": "run", "pop": pops, "cls": a_cls,
                                "l1": a_l1, "l2": a_l2, "d1": a_d1,
                                "d2": a_d2, "act": a_act,
                                "prio": top_cost, "code": int(_code),
                                "s1": obs_audit.b64(app1),
                                "s2": (
                                    obs_audit.b64(app2)
                                    if node.is_dual else None
                                ),
                                "tail": obs_audit.tail(
                                    node.consensus1 + app1
                                ),
                            })
                        if steps > 0:
                            # the branches advanced past the prefetched children
                            self._drop_prefetch(scorer, node)

                            def extend_tables(length):
                                _extend_active_tables(
                                    cfg,
                                    activate_points,
                                    total_active_count,
                                    active_min_count,
                                    length,
                                )

                            kind_constraint = (
                                dual_last_constraint
                                if node.is_dual
                                else single_last_constraint
                            )
                            farthest_kind, kind_constraint = (
                                replay_run_bookkeeping(
                                    kind_tracker,
                                    cfg,
                                    top_len,
                                    steps,
                                    farthest_kind,
                                    kind_constraint,
                                    on_length=extend_tables,
                                )
                            )
                            nodes_explored += steps
                            if node.is_dual:
                                farthest_dual = farthest_kind
                                dual_last_constraint = kind_constraint
                            else:
                                farthest_single = farthest_kind
                                single_last_constraint = kind_constraint
                            node.consensus1 = node.consensus1 + app1
                            node.stats1 = stats1
                            if node.is_dual:
                                node.consensus2 = node.consensus2 + app2
                                node.stats2 = stats2
                                for r in range(n_seqs):
                                    if node.active1[r] and not bool(act1[r]):
                                        node.active1[r] = False
                                        node.offsets1[r] = None
                                    if node.active2[r] and not bool(act2[r]):
                                        node.active2[r] = False
                                        node.offsets2[r] = None
                            if not pqueue.push(
                                node.key(), node, node.priority(cost)
                            ):  # pragma: no cover - chain nodes are unique
                                kind_tracker.remove(node.max_consensus_length())
                                self._free_node(scorer, node)
                            continue

            if node.is_dual:
                farthest_dual = max(farthest_dual, top_len)
                dual_last_constraint += 1
                dual_tracker.process(top_len)
            else:
                farthest_single = max(farthest_single, top_len)
                single_last_constraint += 1
                single_tracker.process(top_len)
            nodes_explored += 1

            # -- completion check -------------------------------------
            if node.reached_all_end(cfg.allow_early_termination):
                fin_result, fin_total = self._finalize(scorer, node)
                imbalanced = False
                if node.is_dual:
                    counts1 = sum(fin_result.is_consensus1)
                    counts2 = len(fin_result.is_consensus1) - counts1
                    # note is_consensus1 already reflects any swap; the
                    # imbalance test is symmetric so that is irrelevant
                    imbalanced = (
                        counts1 < full_min_count or counts2 < full_min_count
                    )
                if not imbalanced:
                    maximum_error = accept_record(
                        maximum_error, results, fin_total, fin_result,
                        cfg.max_return_size,
                    )
                else:
                    logger.debug("Finalized node is imbalanced, ignoring.")
                if audit is not None:
                    audit.emit({
                        "kind": "final", "pop": pops, "cls": a_cls,
                        "l1": a_l1, "l2": a_l2, "d1": a_d1, "d2": a_d2,
                        "act": a_act, "score": int(fin_total),
                        "imbalanced": imbalanced,
                    })

            # -- maintain the dynamic active-count tables -------------
            _extend_active_tables(
                cfg, activate_points, total_active_count, active_min_count,
                top_len,
            )

            # -- extension ---------------------------------------------
            self._expand(
                scorer,
                node,
                activate_points,
                pqueue,
                single_tracker,
                dual_tracker,
                cost,
                audit=audit,
                audit_ctx=(
                    {
                        "kind": "branch", "pop": pops, "cls": a_cls,
                        "l1": a_l1, "l2": a_l2, "d1": a_d1, "d2": a_d2,
                        "act": a_act, "prio": top_cost,
                        "tail": obs_audit.tail(node.consensus1),
                    }
                    if audit is not None
                    else None
                ),
            )
            self._free_node(scorer, node)

            check_invariant(
                len(pqueue)
                == single_tracker.unfiltered_len() + dual_tracker.unfiltered_len(),
                "queue and trackers in sync",
            )

        check_invariant(len(single_tracker) == 0, "single tracker drained")
        check_invariant(len(dual_tracker) == 0, "dual tracker drained")

        if len(results) > 1:
            results.sort(
                key=lambda dc: (
                    dc.consensus1.sequence,
                    dc.consensus2.sequence if dc.consensus2 is not None else b"",
                )
            )

        if not results:
            logger.warning(
                "No consensus found that reached end, is there a gap between "
                "input sequences?"
            )
            results.append(
                DualConsensus(
                    Consensus(b"", cost, [0] * n_seqs),
                    None,
                    [True] * n_seqs,
                    [0] * n_seqs,
                    [None] * n_seqs,
                )
            )

        #: search-shape observability for bench.py / profiling; the
        #: public ``consensus()`` wrapper turns this into a SearchReport
        counters_after = dict(getattr(scorer, "counters", {}))
        self.last_search_stats = {
            "nodes_explored": nodes_explored,
            "nodes_ignored": nodes_ignored,
            "peak_queue_size": peak_queue_size,
            "scorer_counters": {
                k: v - counters_before.get(k, 0)
                for k, v in counters_after.items()
            },
            "backend": getattr(scorer, "timed_backend", None)
            or getattr(scorer, "backend", None) or cfg.backend,
        }
        from waffle_con_tpu.runtime.watchdog import enforce_dispatch_budget

        enforce_dispatch_budget(
            cfg, self.last_search_stats["scorer_counters"], "dual"
        )
        return results

    # ==================================================================
    # checkpoint / resume

    def snapshot(self) -> Optional["ckpt_mod.SearchCheckpoint"]:
        """The most recent :class:`SearchCheckpoint` built for this
        engine's search (by the installed
        :class:`~waffle_con_tpu.models.checkpoint.CheckpointController`),
        or ``None`` — survives a preempted/expired search."""
        return getattr(self, "_last_checkpoint", None)

    @staticmethod
    def _encode_dual_result(d: DualConsensus) -> Dict:
        def enc(c):
            return None if c is None else {
                "sequence": ckpt_mod.b64(c.sequence),
                "scores": [int(s) for s in c.scores],
            }

        return {
            "consensus1": enc(d.consensus1),
            "consensus2": enc(d.consensus2),
            "is_consensus1": [1 if b else 0 for b in d.is_consensus1],
            "scores1": [None if s is None else int(s) for s in d.scores1],
            "scores2": [None if s is None else int(s) for s in d.scores2],
        }

    @staticmethod
    def _decode_dual_result(obj: Dict, cost: ConsensusCost) -> DualConsensus:
        def dec(c):
            return None if c is None else Consensus(
                ckpt_mod.unb64(c["sequence"]), cost,
                [int(s) for s in c["scores"]],
            )

        return DualConsensus(
            dec(obj["consensus1"]),
            dec(obj["consensus2"]),
            [bool(b) for b in obj["is_consensus1"]],
            [None if s is None else int(s) for s in obj["scores1"]],
            [None if s is None else int(s) for s in obj["scores2"]],
        )

    def _checkpoint_body(
        self, pqueue, single_tracker, dual_tracker, *, maximum_error,
        farthest_single, farthest_dual, single_last_constraint,
        dual_last_constraint, nodes_explored, nodes_ignored,
        peak_queue_size, pops, results, total_active_count,
        active_min_count,
    ) -> Dict:
        """JSON checkpoint body at a pop boundary (single-engine twin:
        :meth:`ConsensusDWFA._checkpoint_body`).  Node identity is the
        host-level tuple per side — consensus bytes, active sets,
        offsets, split locks; wavefronts rebuild through the dispatch
        seam on resume.  The ``mc_tab``/``imb_tab`` device tables are
        pure functions of config + activation schedule and are never
        serialized."""
        entries = []
        for _key, nd, pri, seq in pqueue.export_entries():
            entries.append({
                "is_dual": 1 if nd.is_dual else 0,
                "lock1": 1 if nd.lock1 else 0,
                "lock2": 1 if nd.lock2 else 0,
                "consensus1": ckpt_mod.b64(nd.consensus1),
                "consensus2": ckpt_mod.b64(nd.consensus2),
                "active1": [1 if a else 0 for a in nd.active1],
                "active2": [1 if a else 0 for a in nd.active2],
                "offsets1": [o if o is None else int(o)
                             for o in nd.offsets1],
                "offsets2": [o if o is None else int(o)
                             for o in nd.offsets2],
                "priority": [int(p) for p in pri],
                "seq": int(seq),
            })
        return {
            "kind": "dual",
            "config": ckpt_mod.encode_config_dict(self.config),
            "reads": [ckpt_mod.b64(s) for s in self.sequences],
            "offsets": [o if o is None else int(o) for o in self.offsets],
            "state": {
                "entries": entries,
                "queue_seq": pqueue.export_seq(),
                "single_tracker": single_tracker.export_state(),
                "dual_tracker": dual_tracker.export_state(),
                "maximum_error": (None if maximum_error == math.inf
                                  else int(maximum_error)),
                "farthest_single": int(farthest_single),
                "farthest_dual": int(farthest_dual),
                "single_last_constraint": int(single_last_constraint),
                "dual_last_constraint": int(dual_last_constraint),
                "nodes_explored": int(nodes_explored),
                "nodes_ignored": int(nodes_ignored),
                "peak_queue_size": int(peak_queue_size),
                "pops": int(pops),
                "total_active_count": [int(n) for n in total_active_count],
                "active_min_count": [int(n) for n in active_min_count],
                "results": [self._encode_dual_result(d) for d in results],
            },
        }

    def _restore_search(
        self, restore, scorer, pqueue, single_tracker, dual_tracker,
        cost, total_active_count, active_min_count,
    ):
        """Rebuild the mid-search state captured by
        :meth:`_checkpoint_body`; returns the loop-local tuple.  Each
        side of each node rebuilds through the dispatch seam — fresh
        root, the side's consensus replayed through ``push`` (see
        :func:`~waffle_con_tpu.models.consensus._replay_consensus`:
        device backends need the branch-internal buffer filled before
        ``activate`` can catch a wavefront up), then one activate per
        tracked read — bit-identical on any backend; stored priorities
        double as the integrity check."""
        st = restore["state"]
        extra = int(restore.get("extra", 0))
        n_total = len(self.sequences)
        n_base = n_total - extra
        try:
            if not extra:
                single_tracker.restore_state(st["single_tracker"])
                dual_tracker.restore_state(st["dual_tracker"])
                total_active_count = [
                    int(n) for n in st["total_active_count"]
                ]
                active_min_count = [
                    int(n) for n in st["active_min_count"]
                ]
            results = [
                self._decode_dual_result(r, cost) for r in st["results"]
            ]
            maximum_error = (math.inf if st["maximum_error"] is None
                             else int(st["maximum_error"]))
            staged = []
            replay_specs = []
            for entry in st["entries"]:
                node = _DualNode()
                node.is_dual = bool(entry["is_dual"])
                node.lock1 = bool(entry["lock1"])
                node.lock2 = bool(entry["lock2"])
                node.consensus1 = ckpt_mod.unb64(entry["consensus1"])
                node.consensus2 = ckpt_mod.unb64(entry["consensus2"])
                node.active1 = [bool(a) for a in entry["active1"]]
                node.active2 = [bool(a) for a in entry["active2"]]
                node.offsets1 = [o if o is None else int(o)
                                 for o in entry["offsets1"]]
                node.offsets2 = [o if o is None else int(o)
                                 for o in entry["offsets2"]]
                if (len(node.active1) != n_base
                        or len(node.active2) != n_base
                        or len(node.offsets1) != n_base
                        or len(node.offsets2) != n_base):
                    raise ckpt_mod.CheckpointRejected(
                        "node read-count mismatch vs checkpoint reads"
                    )
                # incremental reads join side 1 at offset 0 (pop-0 only)
                node.active1 += [True] * extra
                node.active2 += [False] * extra
                node.offsets1 += [0] * extra
                node.offsets2 += [None] * extra
                node.h1 = scorer.root(np.zeros(n_total, dtype=bool))
                replay_specs.append((node.h1, node.consensus1))
                if node.is_dual:
                    node.h2 = scorer.root(np.zeros(n_total, dtype=bool))
                    replay_specs.append((node.h2, node.consensus2))
                staged.append((entry, node))
            _replay_consensus(scorer, replay_specs)
            for entry, node in staged:
                for r, is_active in enumerate(node.active1):
                    if is_active:
                        scorer.activate(
                            node.h1, r, node.offsets1[r], node.consensus1
                        )
                node.stats1 = scorer.stats(node.h1, node.consensus1)
                if node.is_dual:
                    for r, is_active in enumerate(node.active2):
                        if is_active:
                            scorer.activate(
                                node.h2, r, node.offsets2[r],
                                node.consensus2,
                            )
                    node.stats2 = scorer.stats(node.h2, node.consensus2)
                prio = node.priority(cost)
                if not extra and tuple(int(p) for p in prio) != tuple(
                    int(p) for p in entry["priority"]
                ):
                    raise ckpt_mod.CheckpointRejected(
                        "restored node priority mismatch — checkpoint "
                        "does not match its reads/config"
                    )
                if extra:
                    tracker = (dual_tracker if node.is_dual
                               else single_tracker)
                    tracker.insert(node.max_consensus_length())
                pqueue.push_restored(
                    node.key(), node, prio, int(entry["seq"])
                )
            pqueue.restore_seq(int(st["queue_seq"]))
            if extra:
                # the wider read set invalidates accepted results and
                # the cost bound; the search re-derives both
                results = []
                maximum_error = math.inf
            return (
                maximum_error,
                int(st["farthest_single"]),
                int(st["farthest_dual"]),
                int(st["single_last_constraint"]),
                int(st["dual_last_constraint"]),
                int(st["nodes_explored"]),
                int(st["nodes_ignored"]),
                int(st["peak_queue_size"]),
                int(st["pops"]),
                results,
                total_active_count,
                active_min_count,
            )
        except ckpt_mod.CheckpointError:
            raise
        except (KeyError, TypeError, ValueError, IndexError) as exc:
            raise ckpt_mod.CheckpointRejected(
                f"malformed dual-engine checkpoint state: {exc}"
            ) from None

    @classmethod
    def resume(
        cls, checkpoint, extra_reads=()
    ) -> "DualConsensusDWFA":
        """An engine primed to continue ``checkpoint`` (a
        :class:`SearchCheckpoint` or its wire-dict form); run
        :meth:`consensus` on it to finish the search byte-identically.
        ``extra_reads`` are only accepted on a pop-0 checkpoint (before
        any split decisions the new reads never voted on)."""
        body = ckpt_mod.resume_body(checkpoint, "dual")
        try:
            config = ckpt_mod.decode_config_dict(body["config"])
            reads = [ckpt_mod.unb64(r) for r in body["reads"]]
            offsets = [o if o is None else int(o)
                       for o in body["offsets"]]
            state = body["state"]
            if not isinstance(state, dict) or len(reads) != len(offsets):
                raise ckpt_mod.CheckpointRejected(
                    "malformed dual-engine checkpoint body"
                )
        except ckpt_mod.CheckpointError:
            raise
        except (KeyError, TypeError, ValueError) as exc:
            raise ckpt_mod.CheckpointRejected(
                f"malformed dual-engine checkpoint body: {exc}"
            ) from None
        extras = [bytes(r) for r in extra_reads]
        if extras and int(state.get("pops", -1)) != 0:
            raise ckpt_mod.CheckpointRejected(
                "extra_reads require a pop-0 dual checkpoint (later "
                "snapshots hold split decisions the new reads never "
                "voted on)"
            )
        engine = cls(config)
        for read, offset in zip(reads, offsets):
            engine.add_sequence_offset(read, offset)
        for read in extras:
            engine.add_sequence(read)
        engine._restore_state = {"state": state, "extra": len(extras)}
        return engine

    # ==================================================================
    # arena fast path

    def _arena_attempt(
        self, scorer, pqueue, node, top_cost, maximum_error,
        activate_points, cost, single_tracker, dual_tracker,
        farthest_single, farthest_dual,
        single_last_constraint, dual_last_constraint,
        total_active_count, active_min_count,
        mc_tab, imb_tab,
    ):
        """Engage the device pop arena for the in-hand node plus up to
        ``ARENA_K - 1`` of the next-best queue entries.  Returns ``None``
        when not engaged (competitors incompatible / zero steps committed
        — every popped competitor is restored with its ORIGINAL insertion
        order), else commits the nodes' extensions, materializes any
        children the kernel created at vote splits (``create_mode=2``:
        singles, split pairs, dual cross products — the host expansion
        the arena absorbed), replays the exact per-pop tracker
        bookkeeping, and returns the updated ``(farthest_single,
        farthest_dual, single_last_constraint, dual_last_constraint,
        explored, ignored)``."""
        cfg = self.config
        if pqueue.is_empty():
            return None  # no competitor: the plain run path is strictly better

        # collect the next-best compatible competitors, in pop order; the
        # first ineligible entry becomes the arena's rest-of-queue bound
        fp = fast_paths(scorer)
        taken = []
        take_max = fp.arena_take_max
        while len(taken) < take_max and not pqueue.is_empty():
            cand, pri, seq = pqueue.pop_with_seq()
            if cand.is_dual and (cand.lock1 or cand.lock2):
                pqueue.push_restored(cand.key(), cand, pri, seq)
                break
            taken.append((cand, pri, seq))
        if not taken:
            return None

        def restore_all():
            for cand, pri, seq in taken:
                pqueue.push_restored(cand.key(), cand, pri, seq)

        nodes = [node] + [t[0] for t in taken]
        step_limit = fp.arena_cap
        for nd in nodes:
            nl = nd.max_consensus_length()
            next_act = min((l for l in activate_points if l > nl), default=None)
            if next_act is not None:
                step_limit = min(step_limit, next_act - nl - 1)
        if step_limit < 1:
            restore_all()
            return None

        rest = pqueue.peek_priority()
        rest_cost = 2**31 - 1
        rest_len = 0
        if rest is not None:
            rest_cost = -rest[0]
            rest_len = rest[1]

        needed = (
            max(
                max(nd.max_consensus_length() for nd in nodes),
                farthest_single,
                farthest_dual,
            )
            + fp.arena_cap
            + 4
        )
        win_len = 1 << (needed - 1).bit_length()
        lc_s, pc_s = single_tracker.export_windows(win_len)
        lc_d, pc_d = dual_tracker.export_windows(win_len)
        tr_scalars = [
            [
                single_tracker.threshold(), len(single_tracker),
                farthest_single, single_last_constraint,
            ],
            [
                dual_tracker.threshold(), len(dual_tracker),
                farthest_dual, dual_last_constraint,
            ],
        ]
        me_budget = (
            int(maximum_error) if maximum_error != math.inf else 2**31 - 1
        )
        (events, nsteps, _code, _stop_node, node_steps, appended,
         sides_stats, sides_act, alive, creations) = fp.run_arena(
            [
                (
                    nd.h1,
                    nd.h2 if nd.is_dual else None,
                    len(nd.consensus1),
                    len(nd.consensus2),
                )
                for nd in nodes
            ],
            me_budget,
            cfg.min_count,
            cfg.dual_max_ed_delta,
            cfg.min_count,  # imb_min fallback (imb_tab below is the truth)
            cost is ConsensusCost.L2_DISTANCE,
            cfg.weighted_by_ed,
            rest_cost,
            rest_len,
            cfg.max_queue_size,
            cfg.max_capacity_per_size,
            step_limit,
            cfg.max_nodes_wo_constraint,
            np.stack([lc_s, lc_d]),
            np.stack([pc_s, pc_d]),
            np.asarray(tr_scalars, dtype=np.int32),
            create_mode=2,
            mc_tab=mc_tab,
            imb_tab=imb_tab,
            split_relax=(cfg.min_af == 0.0),
            mc_dyn=(cfg.min_af != 0.0),
        )
        if nsteps == 0:
            restore_all()
            return None

        n_live = len(nodes)
        for i, nd in enumerate(nodes):
            if node_steps[i] > 0 or not alive[i]:
                self._drop_prefetch(scorer, nd)

        # exact tracker replay of the committed interleaved pop sequence
        # (mirrors the engine's per-pop order: constrict both kinds,
        # remove, process, insert; the in-hand first pop was already
        # constricted and removed before the arena engaged).  lens/kinds
        # grow as on-device-created children are registered.
        kinds = [1 if nd.is_dual else 0 for nd in nodes]
        lens = [nd.max_consensus_length() for nd in nodes]
        far = [farthest_single, farthest_dual]
        lcon = [single_last_constraint, dual_last_constraint]
        trackers = (single_tracker, dual_tracker)
        replay_arena_history(
            events, lens, kinds, trackers, far, lcon, cfg,
            creations=creations,
            on_length=lambda length: _extend_active_tables(
                cfg, activate_points, total_active_count, active_min_count,
                length,
            ),
        )
        # kind-split step attribution for the engagement metrics
        committed = sum(1 for k, _ in events if k == "commit")
        arena_dual = sum(
            1 for k, a in events if k == "commit" and kinds[a] == 1
        )
        scorer.counters["arena_dual_steps"] = (
            scorer.counters.get("arena_dual_steps", 0) + arena_dual
        )
        scorer.counters["arena_single_steps"] = (
            scorer.counters.get("arena_single_steps", 0)
            + (committed - arena_dual)
        )

        # apply extensions to the ORIGINAL nodes first (a split-consumed
        # parent keeps its committed prefix so children can build on it)
        for i, nd in enumerate(nodes):
            if node_steps[i] == 0:
                continue
            s1, s2 = 2 * i, 2 * i + 1
            nd.consensus1 = nd.consensus1 + appended[s1]
            nd.stats1 = sides_stats[s1]
            if nd.is_dual:
                nd.consensus2 = nd.consensus2 + appended[s2]
                nd.stats2 = sides_stats[s2]
            a1 = sides_act[s1]
            a2 = sides_act[s2] if nd.is_dual else None
            for r in range(len(nd.active1)):
                if nd.active1[r] and not bool(a1[r]):
                    nd.active1[r] = False
                    nd.offsets1[r] = None
                if a2 is not None and nd.active2[r] and not bool(a2[r]):
                    nd.active2[r] = False
                    nd.offsets2[r] = None

        # materialize on-device-created children as real search nodes
        # (creation order: a child's parent — possibly itself a child —
        # is always already built).  Consensus = the parent side's final
        # committed prefix + the pushed symbol + the child's own arena
        # commits; active/offsets come from the device act rows (which
        # already include divergence pruning at creation).
        all_nodes = list(nodes)
        for j, cre in enumerate(creations):
            idx = n_live + j
            parent = all_nodes[cre["parent"]]
            s1, s2 = 2 * idx, 2 * idx + 1
            pre1 = parent.consensus1[: cre["created_len"] - 1]
            child = _DualNode()
            child.is_dual = cre["kind"] == 1
            child.h1 = cre["h1"]
            child.consensus1 = (
                pre1 + bytes([cre["sym1"]]) + appended[s1]
            )
            a1 = sides_act[s1]
            child.active1 = [bool(a) for a in a1[: len(parent.active1)]]
            child.offsets1 = [
                parent.offsets1[r] if child.active1[r] else None
                for r in range(len(parent.active1))
            ]
            child.stats1 = sides_stats[s1]
            if child.is_dual:
                side2_single = not parent.is_dual
                src_off2 = (
                    parent.offsets1 if side2_single else parent.offsets2
                )
                pre2 = (
                    parent.consensus1 if side2_single else parent.consensus2
                )[: cre["created_len"] - 1]
                child.h2 = cre["h2"]
                child.consensus2 = (
                    pre2 + bytes([cre["sym2"]]) + appended[s2]
                )
                a2 = sides_act[s2]
                child.active2 = [bool(a) for a in a2[: len(parent.active1)]]
                child.offsets2 = [
                    src_off2[r] if child.active2[r] else None
                    for r in range(len(parent.active1))
                ]
                child.stats2 = sides_stats[s2]
            else:
                child.consensus2 = parent.consensus2
                child.active2 = list(parent.active2)
                child.offsets2 = list(parent.offsets2)
            all_nodes.append(child)

        # re-queue: extended nodes re-enter in the order of their LAST
        # arena pop, children at their creation position (later pop ->
        # newer insertion seq); never-popped competitors keep their
        # original seq (FIFO tie order preserved)
        def on_duplicate(idx, nd):
            # two nodes converged to one key: handled like every other
            # insertion path (_queue_child) — drop the newcomer and
            # undo its replayed tracker insert
            logger.warning("duplicate dual search node (arena re-queue)")
            trackers[kinds[idx]].remove(nd.max_consensus_length())
            self._free_node(scorer, nd)

        requeue_arena_nodes(
            pqueue, all_nodes, taken, node_steps, events, cost,
            on_duplicate, alive=alive, n_live=n_live,
        )
        # dead nodes: on-device discards, split-consumed parents, and
        # children that died after creation — all freed here
        for i, nd in enumerate(all_nodes):
            if not alive[i]:
                self._free_node(scorer, nd)
        explored = committed + sum(1 for k, _ in events if k == "split")
        ignored = sum(1 for k, _ in events if k == "discard")
        return far[0], far[1], lcon[0], lcon[1], explored, ignored

    # ==================================================================
    # node helpers

    def _free_node(self, scorer: WavefrontScorer, node: _DualNode) -> None:
        if node.h1 is not None:
            scorer.free(node.h1)
        if node.h2 is not None:
            scorer.free(node.h2)
        node.h1 = node.h2 = None
        self._drop_prefetch(scorer, node)

    def _drop_prefetch(self, scorer: WavefrontScorer, node: _DualNode) -> None:
        if node.prefetch is not None:
            _specs, children = node.prefetch
            node.prefetch = None
            for child in children:
                self._free_node(scorer, child)

    def _activate_sequence(self, scorer, node: _DualNode, seq_index: int) -> None:
        cfg = self.config
        sides = [(True, node.consensus1)]
        if node.is_dual:
            sides.append((False, node.consensus2))
        for side1, consensus in sides:
            active = node.active1 if side1 else node.active2
            check_invariant(not active[seq_index], "activating an already-active read")
            offset = scorer.best_activation_offset(
                consensus,
                seq_index,
                cfg.offset_window,
                cfg.offset_compare_length,
                cfg.wildcard,
            )
            handle = node.h1 if side1 else node.h2
            scorer.activate(handle, seq_index, offset, consensus)
            active[seq_index] = True
            if side1:
                node.offsets1[seq_index] = offset
            else:
                node.offsets2[seq_index] = offset
        node.stats1 = scorer.stats(node.h1, node.consensus1)
        if node.is_dual:
            node.stats2 = scorer.stats(node.h2, node.consensus2)

    def _maybe_activate(
        self, scorer, node: _DualNode, activate_points: Dict[int, List[int]]
    ) -> None:
        activate_list = activate_points.get(node.max_consensus_length())
        if activate_list:
            for seq_index in activate_list:
                self._activate_sequence(scorer, node, seq_index)

    def _collect_prune(
        self, node: _DualNode, ed_delta: int, deactivations: List[Tuple[int, int]]
    ) -> None:
        """Drop the clearly-worse wavefront of a read tracked on both sides
        (``/root/reference/src/dual_consensus.rs:1030-1045``); the scorer
        deactivations are collected for one batched dispatch."""
        if not node.is_dual:
            return
        for r in range(len(node.active1)):
            if node.active1[r] and node.active2[r]:
                e1 = int(node.stats1.eds[r])
                e2 = int(node.stats2.eds[r])
                if e1 + ed_delta < e2:
                    deactivations.append((node.h2, r))
                    node.active2[r] = False
                    node.offsets2[r] = None
                elif e2 + ed_delta < e1:
                    deactivations.append((node.h1, r))
                    node.active1[r] = False
                    node.offsets1[r] = None

    def _finalize(
        self, scorer, node: _DualNode
    ) -> Tuple[DualConsensus, int]:
        """Finalize a scratch copy of the node, returning the result and its
        total cost; raises when some read was never tracked anywhere."""
        cost = self.config.consensus_cost
        n = len(self.sequences)
        for r in range(n):
            if not node.active1[r] and not (node.is_dual and node.active2[r]):
                raise EngineError(
                    "Finalize called on DWFA that was never initialized."
                )
        fin1 = scorer.finalized_eds(node.h1, node.consensus1)
        fin2 = (
            scorer.finalized_eds(node.h2, node.consensus2)
            if node.is_dual
            else np.zeros(n, dtype=np.int64)
        )
        result, total, _c1, _c2 = build_dual_record(
            cost, n, fin1, fin2, node.active1, node.active2,
            node.consensus1, node.consensus2, node.is_dual,
        )
        return result, total

    # ==================================================================
    # expansion

    def _queue_child(
        self, pqueue, tracker, scorer, child: _DualNode, cost
    ) -> None:
        tracker.insert(child.max_consensus_length())
        if not pqueue.push(child.key(), child, child.priority(cost)):
            logger.warning("duplicate dual search node")
            tracker.remove(child.max_consensus_length())
            self._free_node(scorer, child)

    def _gang_attempt(
        self,
        speculator: FrontierSpeculator,
        scorer: WavefrontScorer,
        pqueue: SetPriorityQueue,
        node: _DualNode,
        gang_w: int,
        me_budget: int,
        other_cost: int,
        other_len: int,
        max_steps: int,
        maximum_error: float,
        l2: bool,
    ) -> None:
        """Frontier-parallel speculation for the dual engine: gang the
        in-hand non-dual node's run with the next-best queued NON-dual
        branches through one ragged dispatch (dual nodes step two
        linked branches, which the single-branch ragged kernel cannot
        express — they keep their solo paired kernel).

        The dual engine never forces a first symbol, so peers speculate
        unforced: their deposit commits steps only while the state wins
        the (predicted) pop, exactly the engage rule their own pop will
        apply — see ``models/consensus.py._gang_attempt`` for the
        validation story."""
        cfg = self.config
        members: List[GangMember] = []
        if not speculator.pending(node.h1):
            members.append(GangMember(
                node.h1, node.consensus1, me_budget, other_cost,
                other_len, max_steps, -1,
            ))
        peeked = pqueue.peek_top(gang_w)
        for i, (pn, pprio) in enumerate(peeked):
            if len(members) >= gang_w:
                break
            if pn.is_dual or -pprio[0] > maximum_error:
                continue
            if speculator.pending(pn.h1):
                continue
            specs = (
                pn.prefetch[0] if pn.prefetch is not None
                else self._build_specs(scorer, pn)
            )
            if not (len(specs) == 1 and specs[0][0] == "single"):
                continue
            if i + 1 < len(peeked):
                nxt = peeked[i + 1][1]
                poc, pol = -nxt[0], nxt[1]
            else:
                poc, pol = 2**31 - 1, 0
            members.append(GangMember(
                pn.h1, pn.consensus1, me_budget, poc, pol, max_steps, -1,
            ))
        if len(members) >= 2:
            speculator.gang(members, cfg.min_count, l2)

    def _build_specs(
        self, scorer, node: _DualNode
    ) -> List[Tuple[str, Optional[int], Optional[int]]]:
        """Decide every child of a node as a (kind, sym1, sym2) spec — a
        pure function of the node's stats (so it can run at prefetch time
        with an identical result)."""
        cfg = self.config
        wildcard = cfg.wildcard
        weighted = cfg.weighted_by_ed

        ec1 = node.candidates(True, scorer.symtab, wildcard, weighted)
        min_count1 = max(
            cfg.min_count, math.ceil(cfg.min_af * sum(ec1.values()))
        )
        max_observed1 = max(ec1.values(), default=float(min_count1))
        active_threshold1 = min(float(min_count1), max_observed1)

        specs: List[Tuple[str, Optional[int], Optional[int]]] = []
        if node.is_dual:
            ec2 = node.candidates(False, scorer.symtab, wildcard, weighted)
            min_count2 = max(
                cfg.min_count, math.ceil(cfg.min_af * sum(ec2.values()))
            )
            max_observed2 = max(ec2.values(), default=float(min_count2))
            active_threshold2 = min(float(min_count2), max_observed2)

            is_con1_finalized = node.reached_consensus_end(
                True, cfg.allow_early_termination
            )
            is_con2_finalized = node.reached_consensus_end(
                False, cfg.allow_early_termination
            )

            opt_ec1: List[Optional[int]] = []
            if is_con1_finalized or not ec1 or node.lock1:
                opt_ec1.append(None)
            if not node.lock1:
                opt_ec1.extend(
                    sym
                    for sym in sorted(ec1)
                    if ec1[sym] >= active_threshold1
                )

            opt_ec2: List[Optional[int]] = []
            if is_con2_finalized or not ec2 or node.lock2:
                opt_ec2.append(None)
            if not node.lock2:
                opt_ec2.extend(
                    sym
                    for sym in sorted(ec2)
                    if ec2[sym] >= active_threshold2
                )

            check_invariant(bool(opt_ec1 and opt_ec2), "dual extension option sets non-empty")

            specs.extend(
                ("dual", can1, can2)
                for can1 in opt_ec1
                for can2 in opt_ec2
                # extending neither would duplicate the node
                if not (can1 is None and can2 is None)
            )
        else:
            specs.extend(
                ("single", sym, None)
                for sym in sorted(ec1)
                if ec1[sym] >= active_threshold1
            )
            # dual-split generation: every unordered pair of distinct
            # non-wildcard candidates, when at least two meet min_count1
            sorted_candidates = sorted(
                ((-count, sym) for sym, count in ec1.items() if sym != wildcard)
            )
            num_passing = sum(
                1 for negc, _sym in sorted_candidates if -negc >= min_count1
            )
            if num_passing > 1:
                specs.extend(
                    ("split", c1, c2)
                    for i, (_nc1, c1) in enumerate(sorted_candidates)
                    for _nc2, c2 in sorted_candidates[i + 1 :]
                )
        return specs

    def _materialize_expansions(
        self, scorer, nodes: List[_DualNode]
    ) -> None:
        """Build every listed node's children with ONE fused clone
        dispatch and ONE fused push dispatch across all of them, storing
        ``(specs, children)`` on each node's ``prefetch``."""
        per_node_specs = [self._build_specs(scorer, node) for node in nodes]
        clone_push = fast_paths(scorer).clone_push_many

        #: fused-path bookkeeping: (src_handle, consensus|None) per cloned
        #: side, plus where to deliver the resulting (handle, stats)
        fused_specs: List[Tuple[int, Optional[bytes], bool]] = []
        fused_targets: List[Tuple[_DualNode, bool]] = []
        #: legacy-path bookkeeping
        clone_srcs: List[int] = []
        push_specs: List[Tuple[int, bytes]] = []
        push_targets: List[Tuple[_DualNode, bool]] = []

        def check_lock(child: _DualNode, side1: bool) -> None:
            if side1 and child.lock1:
                raise EngineError("Consensus 1 is locked, cannot modify")
            if not side1 and child.lock2:
                raise EngineError("Consensus 2 is locked, cannot modify")

        def fused_side(child, src_handle, sym, side1) -> None:
            """Register one cloned side: push ``sym`` onto it (None =
            clone only); handle+stats assigned after the fused call."""
            if sym is not None:
                check_lock(child, side1)
                if side1:
                    child.consensus1 = child.consensus1 + bytes([sym])
                else:
                    child.consensus2 = child.consensus2 + bytes([sym])
            fused_specs.append(
                (
                    src_handle,
                    (child.consensus1 if side1 else child.consensus2)
                    if sym is not None
                    else None,
                    False,
                )
            )
            fused_targets.append((child, side1))

        if clone_push is None:
            for node, specs in zip(nodes, per_node_specs):
                for kind, _a, _b in specs:
                    if kind == "dual":
                        clone_srcs += [node.h1, node.h2]
                    elif kind == "single":
                        clone_srcs += [node.h1]
                    else:  # split: both sides start from consensus1
                        clone_srcs += [node.h1, node.h1]
            handles = scorer.clone_many(clone_srcs)
        hi = 0

        def queue_push(child: _DualNode, sym: int, side1: bool) -> None:
            check_lock(child, side1)
            if side1:
                child.consensus1 = child.consensus1 + bytes([sym])
                push_specs.append((child.h1, child.consensus1))
            else:
                child.consensus2 = child.consensus2 + bytes([sym])
                push_specs.append((child.h2, child.consensus2))
            push_targets.append((child, side1))

        for node, specs in zip(nodes, per_node_specs):
            children: List[_DualNode] = []
            for kind, a, b in specs:
                child = _DualNode()
                child.consensus1 = node.consensus1
                child.active1 = list(node.active1)
                child.offsets1 = list(node.offsets1)
                child.stats1 = node.stats1
                if kind == "dual":
                    child.is_dual = True
                    child.lock1 = node.lock1
                    child.lock2 = node.lock2
                    child.consensus2 = node.consensus2
                    child.active2 = list(node.active2)
                    child.offsets2 = list(node.offsets2)
                    child.stats2 = node.stats2
                    if clone_push is not None:
                        fused_side(child, node.h1, a, True)
                        fused_side(child, node.h2, b, False)
                        if a is None:
                            child.lock1 = True
                        if b is None:
                            child.lock2 = True
                    else:
                        child.h1, child.h2 = handles[hi], handles[hi + 1]
                        hi += 2
                        if a is not None:
                            queue_push(child, a, True)
                        else:
                            child.lock1 = True
                        if b is not None:
                            queue_push(child, b, False)
                        else:
                            child.lock2 = True
                elif kind == "single":
                    child.consensus2 = node.consensus2
                    child.active2 = list(node.active2)
                    child.offsets2 = list(node.offsets2)
                    if clone_push is not None:
                        fused_side(child, node.h1, a, True)
                    else:
                        child.h1 = handles[hi]
                        hi += 1
                        queue_push(child, a, True)
                else:  # split (/root/reference/src/dual_consensus.rs:957-976)
                    check_invariant(a != b, "dual split needs distinct symbols")
                    child.is_dual = True
                    child.consensus2 = node.consensus1
                    child.active2 = list(node.active1)
                    child.offsets2 = list(node.offsets1)
                    child.stats2 = node.stats1
                    if clone_push is not None:
                        fused_side(child, node.h1, a, True)
                        fused_side(child, node.h1, b, False)
                    else:
                        child.h1, child.h2 = handles[hi], handles[hi + 1]
                        hi += 2
                        queue_push(child, a, True)
                        queue_push(child, b, False)
                children.append(child)
            node.prefetch = (specs, children)

        if clone_push is not None:
            for (child, side1), (handle, stats) in zip(
                fused_targets, clone_push(fused_specs)
            ):
                if side1:
                    child.h1 = handle
                    if stats is not None:
                        child.stats1 = stats
                else:
                    child.h2 = handle
                    if stats is not None:
                        child.stats2 = stats
        else:
            for (child, side1), stats in zip(
                push_targets, scorer.push_many(push_specs)
            ):
                if side1:
                    child.stats1 = stats
                else:
                    child.stats2 = stats

    def _expand(
        self,
        scorer,
        node: _DualNode,
        activate_points,
        pqueue,
        single_tracker,
        dual_tracker,
        cost,
        audit=None,
        audit_ctx=None,
    ) -> None:
        cfg = self.config

        if node.prefetch is None:
            peers = [
                n
                for n, _p in pqueue.peek_top(cfg.prefetch_width - 1)
                if n.prefetch is None
            ]
            self._materialize_expansions(scorer, [node] + peers)
        specs, children = node.prefetch
        node.prefetch = None
        if audit is not None and audit_ctx is not None:
            record = dict(audit_ctx)
            record["specs"] = [
                [
                    kind,
                    None if a is None else int(a),
                    None if b is None else int(b),
                ]
                for kind, a, b in specs
            ]
            audit.emit(record)

        # -- finishing (pop time): activations, batched pruning, queueing
        deactivations: List[Tuple[int, int]] = []
        for child in children:
            self._maybe_activate(scorer, child, activate_points)
            self._collect_prune(child, cfg.dual_max_ed_delta, deactivations)
        scorer.deactivate_many(deactivations)

        for (kind, _a, _b), child in zip(specs, children):
            if kind == "single":
                check_invariant(not child.is_dual, "single child stays single")
                self._queue_child(pqueue, single_tracker, scorer, child, cost)
            else:
                check_invariant(child.is_dual, "dual child stays dual")
                self._queue_child(pqueue, dual_tracker, scorer, child, cost)
