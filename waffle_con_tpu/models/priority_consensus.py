"""Priority / multi consensus via recursive dual splits.

Each input read is a *chain* of sequences (e.g. ``[hpc_compressed,
full_length]``).  A worklist of read groups is repeatedly solved with the
dual engine at the group's current chain level: dual results partition the
group (same level), single results fix that level's consensus and advance
the chain — a binary splitting tree whose leaves are the final consensus
chains.  Capability parity with
``/root/reference/src/priority_consensus.rs:63-341``.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Set, Tuple

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.models.consensus import (
    PROGRESS_LOG_INTERVAL,
    Consensus,
    EngineError,
    check_invariant,
)
from waffle_con_tpu.models.dual_consensus import DualConsensusDWFA
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs.report import run_reported_search as _reported_search
from waffle_con_tpu.ops.scorer import SubsetScorer, make_scorer

logger = logging.getLogger(__name__)


class PriorityConsensus:
    """Final result: one consensus chain per discovered group, plus the
    group index each input read was assigned to."""

    __slots__ = ("consensuses", "sequence_indices")

    def __init__(
        self,
        consensuses: List[List[Consensus]],
        sequence_indices: List[int],
    ) -> None:
        self.consensuses = consensuses
        self.sequence_indices = sequence_indices

    def __eq__(self, rhs) -> bool:
        return (
            isinstance(rhs, PriorityConsensus)
            and self.consensuses == rhs.consensuses
            and self.sequence_indices == rhs.sequence_indices
        )

    def __repr__(self) -> str:
        return (
            f"PriorityConsensus(consensuses={self.consensuses!r}, "
            f"sequence_indices={self.sequence_indices})"
        )


class PriorityConsensusDWFA:
    """Multi-consensus generation by iterated dual splitting over sequence
    chains.

    Example::

        engine = PriorityConsensusDWFA()
        for chain in chains:            # chain: [seq_level0, seq_level1, ...]
            engine.add_sequence_chain(chain)
        result = engine.consensus()
    """

    def __init__(self, config: Optional[CdwfaConfig] = None) -> None:
        self.config = config if config is not None else CdwfaConfig()
        self.sequences: List[List[bytes]] = []
        self.offsets: List[List[Optional[int]]] = []
        self.seed_groups: List[Optional[int]] = []
        self.alphabet: set = set()

    @classmethod
    def with_config(cls, config: CdwfaConfig) -> "PriorityConsensusDWFA":
        return cls(config)

    def add_sequence_chain(self, sequences: List[bytes]) -> None:
        self.add_seeded_sequence_chain(
            sequences, [None] * len(sequences), None
        )

    def add_seeded_sequence_chain(
        self,
        sequences: List[bytes],
        offsets: List[Optional[int]],
        seed_group: Optional[int],
    ) -> None:
        if not sequences:
            raise EngineError("Must provide a non-empty sequences Vec")
        if self.sequences and len(self.sequences[0]) != len(sequences):
            raise EngineError(
                f"Expected sequences Vec of length {len(self.sequences[0])}, "
                f"but got one of length {len(sequences)}"
            )
        sequences = [bytes(s) for s in sequences]
        for sequence in sequences:
            self.alphabet.update(sequence)
        if self.config.wildcard is not None:
            self.alphabet.discard(self.config.wildcard)
        self.sequences.append(sequences)
        self.offsets.append(list(offsets))
        self.seed_groups.append(seed_group)

    @property
    def consensus_cost(self) -> ConsensusCost:
        return self.config.consensus_cost

    # ------------------------------------------------------------------

    def consensus(self) -> PriorityConsensus:
        """Wraps :meth:`_consensus_impl` in a ``search`` tracer span and
        publishes the aggregated :class:`SearchReport` (summed over the
        inner dual-engine group solves) as ``self.last_search_report``."""
        return _reported_search(self, "priority", self._consensus_impl)

    def _consensus_impl(self) -> PriorityConsensus:
        max_split_level = len(self.sequences[0])
        to_split: List[List[bool]] = []
        split_levels: List[int] = []
        consensus_chains: List[List[Consensus]] = []

        # one initial group per distinct seed (deterministic order)
        initial_group_keys: Set[Optional[int]] = set(self.seed_groups)
        for igk in sorted(initial_group_keys, key=lambda k: (k is not None, k)):
            to_split.append([sg == igk for sg in self.seed_groups])
            split_levels.append(0)
            consensus_chains.append([])

        consensuses: List[List[Consensus]] = []
        assignments: List[List[bool]] = []
        # one device scorer per chain level, shared across every worklist
        # group at that level: the reference re-creates the whole engine
        # per group (src/priority_consensus.rs:201-211), which on a device
        # backend would re-upload the reads and re-compile every kernel
        # for each group's geometry.  A SubsetScorer view gives each group
        # identical semantics over the shared state (a group is just the
        # root activation mask), so only ONE scorer is constructed per
        # level per consensus() call.
        level_scorers: dict = {}
        merged_counters: dict = {}
        scorer_constructions = 0
        total_explored = 0
        total_ignored = 0
        peak_queue_size = 0
        last_backend = None
        share_scorer = self.config.backend == "jax"
        groups_solved = 0
        while to_split:
            include_set = to_split.pop()
            current_split_level = split_levels.pop()
            current_chain = consensus_chains.pop()
            groups_solved += 1
            if groups_solved % PROGRESS_LOG_INTERVAL == 0:
                logger.debug(
                    "search progress: %d groups solved, worklist=%d, "
                    "level=%d", groups_solved, len(to_split),
                    current_split_level,
                )
                if obs_metrics.metrics_enabled():
                    obs_metrics.registry().gauge(
                        "waffle_search_queue_depth", engine="priority"
                    ).set(len(to_split))

            injected = None
            if share_scorer:
                base = level_scorers.get(current_split_level)
                if base is None:
                    base = make_scorer(
                        [chain[current_split_level] for chain in self.sequences],
                        self.config,
                    )
                    level_scorers[current_split_level] = base
                    scorer_constructions += 1
                indices = [i for i, inc in enumerate(include_set) if inc]
                injected = SubsetScorer(base, indices)
            else:
                scorer_constructions += 1  # the dual engine builds its own
            dc_dwfa = DualConsensusDWFA(self.config, scorer=injected)
            logger.debug(
                "Calling Dual at level %d with: %s", current_split_level, include_set
            )
            for include, (seq_chain, offset_chain) in zip(
                include_set, zip(self.sequences, self.offsets)
            ):
                if include:
                    dc_dwfa.add_sequence_offset(
                        seq_chain[current_split_level],
                        offset_chain[current_split_level],
                    )

            dc_result = dc_dwfa.consensus()
            inner_stats = dc_dwfa.last_search_stats
            for k, v in inner_stats["scorer_counters"].items():
                merged_counters[k] = merged_counters.get(k, 0) + v
            total_explored += inner_stats.get("nodes_explored", 0)
            total_ignored += inner_stats.get("nodes_ignored", 0)
            peak_queue_size = max(
                peak_queue_size, inner_stats.get("peak_queue_size", 0)
            )
            last_backend = inner_stats.get("backend", last_backend)
            if len(dc_result) > 1:
                logger.debug(
                    "Multiple dual consensuses detected, arbitrarily selecting "
                    "first option."
                )
            chosen = dc_result[0]

            if chosen.is_dual():
                # partition the group by assignment; both halves re-split at
                # the same chain level
                is_c1 = chosen.is_consensus1
                assign1 = [False] * len(self.sequences)
                assign2 = [False] * len(self.sequences)
                ic_index = 0
                for i, included in enumerate(include_set):
                    if included:
                        if is_c1[ic_index]:
                            assign1[i] = True
                        else:
                            assign2[i] = True
                        ic_index += 1
                check_invariant(ic_index == len(is_c1), "assignment vector fully consumed")

                to_split.append(assign1)
                split_levels.append(current_split_level)
                consensus_chains.append(list(current_chain))
                to_split.append(assign2)
                split_levels.append(current_split_level)
                consensus_chains.append(current_chain)
            else:
                new_split_level = current_split_level + 1
                current_chain.append(chosen.consensus1)
                if new_split_level == max_split_level:
                    consensuses.append(current_chain)
                    assignments.append(include_set)
                else:
                    to_split.append(include_set)
                    split_levels.append(new_split_level)
                    consensus_chains.append(current_chain)

            # evict shared scorers no pending group can reach (levels only
            # ever increase per group), releasing their device state
            if share_scorer and level_scorers:
                alive = set(split_levels)
                for lvl in [l for l in level_scorers if l not in alive]:
                    del level_scorers[lvl]

        #: aggregated per-group scorer-counter deltas (bench.py /
        #: profiling observability); scorer_constructions is the
        #: per-consensus() ctor count the sharing exists to minimize;
        #: search-shape numbers are summed (peak: max) over the inner
        #: dual-engine group solves
        self.last_search_stats = {
            "scorer_counters": merged_counters,
            "scorer_constructions": scorer_constructions,
            "nodes_explored": total_explored,
            "nodes_ignored": total_ignored,
            "peak_queue_size": peak_queue_size,
            "backend": last_backend or self.config.backend,
        }
        from waffle_con_tpu.runtime.watchdog import enforce_dispatch_budget

        enforce_dispatch_budget(self.config, merged_counters, "priority")

        if len(consensuses) > 1:
            indices = [-1] * len(self.sequences)
            order = sorted(
                range(len(consensuses)),
                key=lambda i: [c.sequence for c in consensuses[i]],
            )
            sorted_cons = []
            for con_index, old_index in enumerate(order):
                for i, assigned in enumerate(assignments[old_index]):
                    if assigned:
                        check_invariant(indices[i] == -1, "sequence index remapped once")
                        indices[i] = con_index
                sorted_cons.append(consensuses[old_index])
            return PriorityConsensus(sorted_cons, indices)
        return PriorityConsensus(consensuses, [0] * len(self.sequences))
