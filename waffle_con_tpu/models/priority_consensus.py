"""Priority / multi consensus via recursive dual splits.

Each input read is a *chain* of sequences (e.g. ``[hpc_compressed,
full_length]``).  A worklist of read groups is repeatedly solved with the
dual engine at the group's current chain level: dual results partition the
group (same level), single results fix that level's consensus and advance
the chain — a binary splitting tree whose leaves are the final consensus
chains.  Capability parity with
``/root/reference/src/priority_consensus.rs:63-341``.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set, Tuple

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.models import checkpoint as ckpt_mod
from waffle_con_tpu.models.consensus import (
    PROGRESS_LOG_INTERVAL,
    Consensus,
    EngineError,
    check_invariant,
)
from waffle_con_tpu.models.dual_consensus import DualConsensusDWFA
from waffle_con_tpu.obs import audit as obs_audit
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs.report import run_reported_search as _reported_search
from waffle_con_tpu.ops.scorer import SubsetScorer, make_scorer

logger = logging.getLogger(__name__)


class PriorityConsensus:
    """Final result: one consensus chain per discovered group, plus the
    group index each input read was assigned to."""

    __slots__ = ("consensuses", "sequence_indices")

    def __init__(
        self,
        consensuses: List[List[Consensus]],
        sequence_indices: List[int],
    ) -> None:
        self.consensuses = consensuses
        self.sequence_indices = sequence_indices

    def __eq__(self, rhs) -> bool:
        return (
            isinstance(rhs, PriorityConsensus)
            and self.consensuses == rhs.consensuses
            and self.sequence_indices == rhs.sequence_indices
        )

    def __repr__(self) -> str:
        return (
            f"PriorityConsensus(consensuses={self.consensuses!r}, "
            f"sequence_indices={self.sequence_indices})"
        )


class PriorityConsensusDWFA:
    """Multi-consensus generation by iterated dual splitting over sequence
    chains.

    Example::

        engine = PriorityConsensusDWFA()
        for chain in chains:            # chain: [seq_level0, seq_level1, ...]
            engine.add_sequence_chain(chain)
        result = engine.consensus()
    """

    def __init__(self, config: Optional[CdwfaConfig] = None) -> None:
        self.config = config if config is not None else CdwfaConfig()
        self.sequences: List[List[bytes]] = []
        self.offsets: List[List[Optional[int]]] = []
        self.seed_groups: List[Optional[int]] = []
        self.alphabet: set = set()

    @classmethod
    def with_config(cls, config: CdwfaConfig) -> "PriorityConsensusDWFA":
        return cls(config)

    def add_sequence_chain(self, sequences: List[bytes]) -> None:
        self.add_seeded_sequence_chain(
            sequences, [None] * len(sequences), None
        )

    def add_seeded_sequence_chain(
        self,
        sequences: List[bytes],
        offsets: List[Optional[int]],
        seed_group: Optional[int],
    ) -> None:
        if not sequences:
            raise EngineError("Must provide a non-empty sequences Vec")
        if self.sequences and len(self.sequences[0]) != len(sequences):
            raise EngineError(
                f"Expected sequences Vec of length {len(self.sequences[0])}, "
                f"but got one of length {len(sequences)}"
            )
        sequences = [bytes(s) for s in sequences]
        for sequence in sequences:
            self.alphabet.update(sequence)
        if self.config.wildcard is not None:
            self.alphabet.discard(self.config.wildcard)
        self.sequences.append(sequences)
        self.offsets.append(list(offsets))
        self.seed_groups.append(seed_group)

    @property
    def consensus_cost(self) -> ConsensusCost:
        return self.config.consensus_cost

    # ------------------------------------------------------------------

    def consensus(self) -> PriorityConsensus:
        """Wraps :meth:`_consensus_impl` in a ``search`` tracer span and
        publishes the aggregated :class:`SearchReport` (summed over the
        inner dual-engine group solves) as ``self.last_search_report``."""
        return _reported_search(self, "priority", self._consensus_impl)

    def _consensus_impl(self) -> PriorityConsensus:
        restore = getattr(self, "_restore_state", None)
        self._restore_state = None
        max_split_level = len(self.sequences[0])
        to_split: List[List[bool]] = []
        split_levels: List[int] = []
        consensus_chains: List[List[Consensus]] = []

        if restore is None:
            # one initial group per distinct seed (deterministic order)
            initial_group_keys: Set[Optional[int]] = set(self.seed_groups)
            for igk in sorted(
                initial_group_keys, key=lambda k: (k is not None, k)
            ):
                to_split.append([sg == igk for sg in self.seed_groups])
                split_levels.append(0)
                consensus_chains.append([])

        consensuses: List[List[Consensus]] = []
        assignments: List[List[bool]] = []
        # one device scorer per chain level, shared across every worklist
        # group at that level: the reference re-creates the whole engine
        # per group (src/priority_consensus.rs:201-211), which on a device
        # backend would re-upload the reads and re-compile every kernel
        # for each group's geometry.  A SubsetScorer view gives each group
        # identical semantics over the shared state (a group is just the
        # root activation mask), so only ONE scorer is constructed per
        # level per consensus() call.
        level_scorers: dict = {}
        merged_counters: dict = {}
        scorer_constructions = 0
        total_explored = 0
        total_ignored = 0
        peak_queue_size = 0
        last_backend = None
        share_scorer = self.config.backend == "jax"
        groups_solved = 0
        pending: Optional[Tuple] = None
        if restore is not None:
            (to_split, split_levels, consensus_chains, consensuses,
             assignments, merged_counters, scorer_constructions,
             total_explored, total_ignored, peak_queue_size,
             groups_solved, pending) = self._restore_worklist(restore)

        ctrl = ckpt_mod.current_controller()
        #: decision audit sink (``None`` when WAFFLE_AUDIT is off); the
        #: worklist emits one ``group`` marker per group solve — the
        #: inner dual searches record their own per-pop streams
        audit = obs_audit.search_sink("priority")
        include_set: List[bool] = []
        current_split_level = 0
        current_chain: List[Consensus] = []

        def _wrap_body(inner_body: Dict) -> Dict:
            # a closure over the worklist locals, called by the
            # controller while the inner dual solve is mid-search: the
            # popped (in-flight) group travels as ``current`` with the
            # inner dual state embedded, the rest of the worklist and
            # the accumulators as-is
            enc = self._encode_consensus
            return {
                "kind": "priority",
                "config": ckpt_mod.encode_config_dict(self.config),
                "chains": [[ckpt_mod.b64(s) for s in chain]
                           for chain in self.sequences],
                "offsets": [[o if o is None else int(o) for o in chain]
                            for chain in self.offsets],
                "seed_groups": [
                    sg if sg is None else int(sg)
                    for sg in self.seed_groups
                ],
                "state": {
                    "to_split": [[1 if x else 0 for x in row]
                                 for row in to_split],
                    "split_levels": [int(l) for l in split_levels],
                    "consensus_chains": [[enc(c) for c in chain]
                                         for chain in consensus_chains],
                    "consensuses": [[enc(c) for c in chain]
                                    for chain in consensuses],
                    "assignments": [[1 if x else 0 for x in row]
                                    for row in assignments],
                    "merged_counters": {str(k): int(v) for k, v
                                        in merged_counters.items()},
                    "scorer_constructions": int(scorer_constructions),
                    "total_explored": int(total_explored),
                    "total_ignored": int(total_ignored),
                    "peak_queue_size": int(peak_queue_size),
                    "groups_solved": int(groups_solved),
                    "current": {
                        "include_set": [1 if x else 0
                                        for x in include_set],
                        "split_level": int(current_split_level),
                        "chain": [enc(c) for c in current_chain],
                    },
                    "inner": inner_body["state"],
                },
            }

        while to_split or pending is not None:
            if pending is not None:
                # the group in flight when the checkpoint was taken;
                # groups_solved already counted it at the original pop
                (include_set, current_split_level, current_chain,
                 inner_state) = pending
                pending = None
            else:
                include_set = to_split.pop()
                current_split_level = split_levels.pop()
                current_chain = consensus_chains.pop()
                inner_state = None
                groups_solved += 1
            if groups_solved % PROGRESS_LOG_INTERVAL == 0:
                logger.debug(
                    "search progress: %d groups solved, worklist=%d, "
                    "level=%d", groups_solved, len(to_split),
                    current_split_level,
                )
                if obs_metrics.metrics_enabled():
                    obs_metrics.registry().gauge(
                        "waffle_search_queue_depth", engine="priority"
                    ).set(len(to_split))

            if audit is not None:
                # one marker per group solve: the worklist's decision
                # unit (the inner dual search emits its own per-pop
                # records through its own sink)
                audit.emit({
                    "kind": "group", "pop": groups_solved,
                    "level": current_split_level,
                    "include": obs_audit.active_digest(
                        i for i, inc in enumerate(include_set) if inc
                    ),
                    "size": sum(1 for inc in include_set if inc),
                })

            injected = None
            if share_scorer:
                base = level_scorers.get(current_split_level)
                if base is None:
                    base = make_scorer(
                        [chain[current_split_level] for chain in self.sequences],
                        self.config,
                    )
                    level_scorers[current_split_level] = base
                    scorer_constructions += 1
                indices = [i for i, inc in enumerate(include_set) if inc]
                injected = SubsetScorer(base, indices)
            else:
                scorer_constructions += 1  # the dual engine builds its own
            dc_dwfa = DualConsensusDWFA(self.config, scorer=injected)
            logger.debug(
                "Calling Dual at level %d with: %s", current_split_level, include_set
            )
            for include, (seq_chain, offset_chain) in zip(
                include_set, zip(self.sequences, self.offsets)
            ):
                if include:
                    dc_dwfa.add_sequence_offset(
                        seq_chain[current_split_level],
                        offset_chain[current_split_level],
                    )

            if inner_state is not None:
                dc_dwfa._restore_state = {"state": inner_state, "extra": 0}
            if ctrl is not None:
                ctrl.push_wrapper(_wrap_body)
            try:
                dc_result = dc_dwfa.consensus()
            finally:
                if ctrl is not None:
                    ctrl.pop_wrapper()
                    self._last_checkpoint = ctrl.last_checkpoint
            inner_stats = dc_dwfa.last_search_stats
            for k, v in inner_stats["scorer_counters"].items():
                merged_counters[k] = merged_counters.get(k, 0) + v
            total_explored += inner_stats.get("nodes_explored", 0)
            total_ignored += inner_stats.get("nodes_ignored", 0)
            peak_queue_size = max(
                peak_queue_size, inner_stats.get("peak_queue_size", 0)
            )
            last_backend = inner_stats.get("backend", last_backend)
            if len(dc_result) > 1:
                logger.debug(
                    "Multiple dual consensuses detected, arbitrarily selecting "
                    "first option."
                )
            chosen = dc_result[0]

            if chosen.is_dual():
                # partition the group by assignment; both halves re-split at
                # the same chain level
                is_c1 = chosen.is_consensus1
                assign1 = [False] * len(self.sequences)
                assign2 = [False] * len(self.sequences)
                ic_index = 0
                for i, included in enumerate(include_set):
                    if included:
                        if is_c1[ic_index]:
                            assign1[i] = True
                        else:
                            assign2[i] = True
                        ic_index += 1
                check_invariant(ic_index == len(is_c1), "assignment vector fully consumed")

                to_split.append(assign1)
                split_levels.append(current_split_level)
                consensus_chains.append(list(current_chain))
                to_split.append(assign2)
                split_levels.append(current_split_level)
                consensus_chains.append(current_chain)
            else:
                new_split_level = current_split_level + 1
                current_chain.append(chosen.consensus1)
                if new_split_level == max_split_level:
                    consensuses.append(current_chain)
                    assignments.append(include_set)
                else:
                    to_split.append(include_set)
                    split_levels.append(new_split_level)
                    consensus_chains.append(current_chain)

            # evict shared scorers no pending group can reach (levels only
            # ever increase per group), releasing their device state
            if share_scorer and level_scorers:
                alive = set(split_levels)
                for lvl in [l for l in level_scorers if l not in alive]:
                    del level_scorers[lvl]

        #: aggregated per-group scorer-counter deltas (bench.py /
        #: profiling observability); scorer_constructions is the
        #: per-consensus() ctor count the sharing exists to minimize;
        #: search-shape numbers are summed (peak: max) over the inner
        #: dual-engine group solves
        self.last_search_stats = {
            "scorer_counters": merged_counters,
            "scorer_constructions": scorer_constructions,
            "nodes_explored": total_explored,
            "nodes_ignored": total_ignored,
            "peak_queue_size": peak_queue_size,
            "backend": last_backend or self.config.backend,
        }
        from waffle_con_tpu.runtime.watchdog import enforce_dispatch_budget

        enforce_dispatch_budget(self.config, merged_counters, "priority")

        if len(consensuses) > 1:
            indices = [-1] * len(self.sequences)
            order = sorted(
                range(len(consensuses)),
                key=lambda i: [c.sequence for c in consensuses[i]],
            )
            sorted_cons = []
            for con_index, old_index in enumerate(order):
                for i, assigned in enumerate(assignments[old_index]):
                    if assigned:
                        check_invariant(indices[i] == -1, "sequence index remapped once")
                        indices[i] = con_index
                sorted_cons.append(consensuses[old_index])
            return PriorityConsensus(sorted_cons, indices)
        return PriorityConsensus(consensuses, [0] * len(self.sequences))

    # -- checkpoint / resume -------------------------------------------

    def snapshot(self) -> Optional["ckpt_mod.SearchCheckpoint"]:
        """The most recent :class:`SearchCheckpoint` built for this
        engine's search (by the installed
        :class:`~waffle_con_tpu.models.checkpoint.CheckpointController`),
        or ``None`` — survives a preempted/expired search."""
        return getattr(self, "_last_checkpoint", None)

    @staticmethod
    def _encode_consensus(c: Consensus) -> Dict:
        return {
            "sequence": ckpt_mod.b64(c.sequence),
            "scores": [int(s) for s in c.scores],
        }

    def _decode_consensus(self, obj: Dict) -> Consensus:
        return Consensus(
            ckpt_mod.unb64(obj["sequence"]),
            self.config.consensus_cost,
            [int(s) for s in obj["scores"]],
        )

    def _restore_worklist(self, restore):
        """Rebuild the worklist state captured by the checkpoint
        wrapper in :meth:`_consensus_impl`; the in-flight group comes
        back as ``pending`` with its embedded inner dual state, which
        the loop re-enters through
        :meth:`DualConsensusDWFA._restore_search`."""
        st = restore["state"]
        dec = self._decode_consensus
        try:
            cur = st["current"]
            pending = (
                [bool(x) for x in cur["include_set"]],
                int(cur["split_level"]),
                [dec(c) for c in cur["chain"]],
                st["inner"],
            )
            if (len(pending[0]) != len(self.sequences)
                    or not isinstance(st["inner"], dict)):
                raise ckpt_mod.CheckpointRejected(
                    "worklist group size mismatch vs checkpoint chains"
                )
            return (
                [[bool(x) for x in row] for row in st["to_split"]],
                [int(l) for l in st["split_levels"]],
                [[dec(c) for c in chain]
                 for chain in st["consensus_chains"]],
                [[dec(c) for c in chain] for chain in st["consensuses"]],
                [[bool(x) for x in row] for row in st["assignments"]],
                {str(k): int(v)
                 for k, v in st["merged_counters"].items()},
                int(st["scorer_constructions"]),
                int(st["total_explored"]),
                int(st["total_ignored"]),
                int(st["peak_queue_size"]),
                int(st["groups_solved"]),
                pending,
            )
        except ckpt_mod.CheckpointError:
            raise
        except (KeyError, TypeError, ValueError, AttributeError) as exc:
            raise ckpt_mod.CheckpointRejected(
                f"malformed priority-engine checkpoint state: {exc}"
            ) from None

    @classmethod
    def resume(
        cls, checkpoint, extra_reads=()
    ) -> "PriorityConsensusDWFA":
        """An engine primed to continue ``checkpoint`` (a
        :class:`SearchCheckpoint` or its wire-dict form); run
        :meth:`consensus` on it to finish the search byte-identically.
        ``extra_reads`` must be empty: chain levels fix the read set
        (stream new reads through the single/dual engines instead)."""
        if tuple(extra_reads):
            raise ckpt_mod.CheckpointRejected(
                "extra_reads are not supported for the priority engine "
                "(sequence chains fix the read set at every level)"
            )
        body = ckpt_mod.resume_body(checkpoint, "priority")
        try:
            config = ckpt_mod.decode_config_dict(body["config"])
            chains = [[ckpt_mod.unb64(s) for s in chain]
                      for chain in body["chains"]]
            offsets = [[o if o is None else int(o) for o in chain]
                       for chain in body["offsets"]]
            seed_groups = [sg if sg is None else int(sg)
                           for sg in body["seed_groups"]]
            state = body["state"]
            if (not isinstance(state, dict)
                    or len(chains) != len(offsets)
                    or len(chains) != len(seed_groups)):
                raise ckpt_mod.CheckpointRejected(
                    "malformed priority-engine checkpoint body"
                )
        except ckpt_mod.CheckpointError:
            raise
        except (KeyError, TypeError, ValueError) as exc:
            raise ckpt_mod.CheckpointRejected(
                f"malformed priority-engine checkpoint body: {exc}"
            ) from None
        engine = cls(config)
        for chain, offset_chain, seed_group in zip(
            chains, offsets, seed_groups
        ):
            engine.add_seeded_sequence_chain(
                chain, offset_chain, seed_group
            )
        engine._restore_state = {"state": state}
        return engine
