"""Single-consensus engine: least-cost-first search over partial consensus
strings, scored by incremental per-read wavefronts.

Capability parity with the reference engine
(``/root/reference/src/consensus.rs:76-570``), re-architected over the
:class:`~waffle_con_tpu.ops.scorer.WavefrontScorer` seam so the per-read
scoring step runs on any backend (Python oracle, C++, batched JAX/TPU).

Example::

    from waffle_con_tpu import ConsensusDWFA

    cdwfa = ConsensusDWFA()
    for s in [b"ACGT", b"ACCGT", b"ACCCGT"]:
        cdwfa.add_sequence(s)
    results = cdwfa.consensus()
    assert results[0].sequence == b"ACCGT"
    assert results[0].scores == [1, 0, 1]
"""

from __future__ import annotations

import logging
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.obs import audit as obs_audit
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs.instrument import FrontierSampler
from waffle_con_tpu.obs.report import run_reported_search as _reported_search
from waffle_con_tpu.models import checkpoint as ckpt_mod
from waffle_con_tpu.models.frontier import FrontierSpeculator, GangMember
from waffle_con_tpu.runtime import faults as faults_mod
from waffle_con_tpu.ops.scorer import (
    BranchStats,
    WavefrontScorer,
    fast_paths,
    make_scorer,
)
from waffle_con_tpu.utils.pqueue import PQueueTracker, SetPriorityQueue

logger = logging.getLogger(__name__)

#: Per-engagement column cap for the device run loops: bounds the host-side
#: bookkeeping simulation; long clean stretches simply re-engage next pop.
RUN_SIM_CAP = 65536

#: Queue pops between search-progress debug lines (reference parity:
#: ``/root/reference/src/dual_consensus.rs:403-414``); tests shrink it.
PROGRESS_LOG_INTERVAL = 1000


class EngineError(Exception):
    """Engine-level failure (coverage gaps, invalid inputs, ...).

    The message strings for reference-visible failures are API surface and
    match the reference exactly (asserted by tests; cf.
    ``/root/reference/src/consensus.rs:850``)."""


def check_invariant(condition: bool, message: str) -> None:
    """Engine invariant check that survives ``python -O`` (unlike
    ``assert``): violations raise :class:`EngineError`."""
    if not condition:
        raise EngineError(f"internal invariant violated: {message}")


class Consensus:
    """A final consensus result: the sequence, the cost model, and the
    per-read scores (parity with ``/root/reference/src/consensus.rs:42-74``)."""

    __slots__ = ("sequence", "consensus_cost", "scores")

    def __init__(
        self,
        sequence: bytes,
        consensus_cost: ConsensusCost,
        scores: List[int],
    ) -> None:
        self.sequence = bytes(sequence)
        self.consensus_cost = consensus_cost
        self.scores = list(scores)

    def __eq__(self, rhs) -> bool:
        return (
            isinstance(rhs, Consensus)
            and self.sequence == rhs.sequence
            and self.consensus_cost == rhs.consensus_cost
            and self.scores == rhs.scores
        )

    def __repr__(self) -> str:
        return (
            f"Consensus(sequence={self.sequence!r}, "
            f"cost={self.consensus_cost.value}, scores={self.scores})"
        )


def shift_offsets(
    offsets: List[Optional[int]], auto_shift: bool
) -> List[Optional[int]]:
    """When no read starts at offset ``None`` and auto-shift is enabled,
    shift every offset down by the minimum (the minimum becomes ``None``);
    parity with ``/root/reference/src/consensus.rs:151-181``."""
    if not auto_shift or any(o is None for o in offsets):
        return list(offsets)
    min_offset = min(offsets)
    logger.debug("No start sequence detected, shifting all offsets by %d", min_offset)
    return [None if o == min_offset else o - min_offset for o in offsets]


def replay_run_bookkeeping(
    tracker: PQueueTracker,
    cfg: CdwfaConfig,
    top_len: int,
    steps: int,
    farthest: int,
    last_constraint: int,
    on_length=None,
) -> Tuple[int, int]:
    """Replay the per-length tracker bookkeeping for a device-committed
    extension run, exactly as the per-symbol host loop would have done it:
    threshold constriction, remove/process/insert, and the farthest /
    constraint counters.  ``on_length`` runs once per replayed length for
    engine-specific tables.  Returns updated ``(farthest,
    last_constraint)``.

    Capacity stops cannot fire mid-run: the run only engages when the node
    is at the frontier (``top_len >= farthest``), so every replayed length
    beyond the first has never been processed, and the first is the pop's
    own process.

    Fast path (no ``on_length``): segments between constriction triggers
    collapse to one vectorized ``bulk_run_advance`` — the queue total is
    constant during a run, so the only mid-run trigger is the
    ``max_nodes_wo_constraint`` counter, whose firing step is computable
    in closed form.
    """
    j = 0
    while on_length is None and j < steps:
        if j > 0:
            # constrict exactly as the scalar loop would before pop j
            while (
                len(tracker) > cfg.max_queue_size
                or last_constraint >= cfg.max_nodes_wo_constraint
            ) and tracker.threshold() < farthest:
                tracker.increment_threshold()
                last_constraint = 0
        # inside a segment the queue total transiently holds one extra
        # entry (each step's insert precedes the next step's remove);
        # if that would trip the queue-size trigger, every inner step
        # would constrict and the closed form breaks — go scalar
        if len(tracker) + 1 > cfg.max_queue_size:
            break
        seg = min(
            steps - j, cfg.max_nodes_wo_constraint - last_constraint
        )
        if seg <= 0:
            break  # budget pinned with threshold at farthest: go scalar
        if not tracker.bulk_run_advance(
            top_len + j, seg, fresh_pop=(j == 0)
        ):
            break  # capacity edge: exact scalar loop handles it
        farthest = max(farthest, top_len + j + seg - 1)
        last_constraint += seg
        j += seg
    for j in range(j, steps):
        length = top_len + j
        if j > 0:
            while (
                len(tracker) > cfg.max_queue_size
                or last_constraint >= cfg.max_nodes_wo_constraint
            ) and tracker.threshold() < farthest:
                tracker.increment_threshold()
                last_constraint = 0
            tracker.remove(length)
        farthest = max(farthest, length)
        last_constraint += 1
        tracker.process(length)
        tracker.insert(length + 1)
        if on_length is not None:
            on_length(length)
    return farthest, last_constraint


def replay_arena_history(
    events, lens, kinds, trackers, far, lcon, cfg, creations=None,
    on_length=None,
):
    """Replay a device arena's committed interleaved pop sequence onto the
    real tracker objects — the ONE copy of the per-pop bookkeeping both
    engines' arena paths share (mirrors the engines' pop order: constrict
    every kind, remove, process, insert; the in-hand first pop was
    already constricted and removed before the arena engaged).

    ``events`` is the typed stream from ``run_arena``:

    - ``("commit", n)`` — a committed extension pop of node ``n``
      (remove, process, insert at length + 1).
    - ``("discard", n)`` — an on-device discarded pop: its queue removal
      is replayed but nothing else (the engine's ignored-pop path).
    - ``("split", n)`` — node ``n``'s pop was consumed by on-device
      child creation: remove + process, NO insert (the node dies; its
      children's inserts follow as their own events).
    - ``("create", j)`` — creation record ``j`` (see ``creations``):
      registers the child at node index ``len(lens)`` and replays its
      tracker insert.  Not a pop — no constriction.

    ``lens``/``kinds`` are mutated in place and GROW as children are
    registered; ``far``/``lcon`` are per kind, matching ``trackers``."""
    first_pop = True
    for kind, arg in events:
        if kind == "create":
            rec = creations[arg]
            lens.append(rec["created_len"])
            kinds.append(rec["kind"])
            trackers[rec["kind"]].insert(rec["created_len"])
            continue
        which = arg
        k = kinds[which]
        length = lens[which]
        if not first_pop:
            for kk in range(len(trackers)):
                while (
                    len(trackers[kk]) > cfg.max_queue_size
                    or lcon[kk] >= cfg.max_nodes_wo_constraint
                ) and trackers[kk].threshold() < far[kk]:
                    trackers[kk].increment_threshold()
                    lcon[kk] = 0
            trackers[k].remove(length)
        first_pop = False
        if kind == "discard":
            continue
        far[k] = max(far[k], length)
        lcon[k] += 1
        trackers[k].process(length)
        if kind == "commit":
            trackers[k].insert(length + 1)
            lens[which] += 1
        if on_length is not None:
            on_length(length)


def requeue_arena_nodes(
    pqueue, nodes, taken, node_steps, events, cost, on_duplicate,
    alive=None, n_live=None,
):
    """Re-queue arena participants preserving insertion order: extended
    nodes re-enter in the order of their LAST arena pop (later pop ->
    newer insertion seq); nodes created on device enter at their
    creation position (or their last pop if they were popped later);
    never-popped competitors keep their original seq (FIFO tie order).
    ``on_duplicate(idx, node)`` handles the rare key collision (drop the
    newcomer, undo its replayed tracker insert).  Nodes discarded or
    consumed by a split on device (``alive[idx]`` False) are never
    re-queued — the caller frees them.  ``nodes`` must cover children
    (indices ``n_live + j`` in creation-record order)."""
    if n_live is None:
        n_live = len(nodes)
    last_pos = {}
    n_created = 0
    for i, (kind, arg) in enumerate(events):
        if kind == "commit":
            last_pos[arg] = i
        elif kind == "create":
            last_pos[n_live + n_created] = i
            n_created += 1
    for i, (cand, pri, seq) in enumerate(taken, start=1):
        if node_steps[i] == 0 and (alive is None or alive[i]):
            ok = pqueue.push_restored(cand.key(), cand, pri, seq)
            check_invariant(ok, "arena restore unique")
    for idx in sorted(last_pos, key=last_pos.get):
        if alive is not None and not alive[idx]:
            continue
        nd = nodes[idx]
        if not pqueue.push(nd.key(), nd, nd.priority(cost)):
            on_duplicate(idx, nd)


def accept_record(maximum_error, results, total, result, max_return_size):
    """THE copy of result acceptance (reference completion semantics,
    ``/root/reference/src/consensus.rs:261-278``): a strictly better
    total resets the budget and clears the tied set; totals at the
    budget append up to ``max_return_size``.  Returns the new budget.
    Shared by the completion paths and the run-record replays so they
    can never drift."""
    if total < maximum_error:
        maximum_error = total
        results.clear()
    if total <= maximum_error and len(results) < max_return_size:
        results.append(result)
    return maximum_error


def candidates_from_stats(
    stats: BranchStats,
    symtab: np.ndarray,
    wildcard: Optional[int],
    weights: Optional[Sequence[float]] = None,
) -> Dict[int, float]:
    """Fold per-read integer tip votes into fractional per-symbol votes.

    Each read splits one unit of vote across its tip symbols
    (``occ/split``), optionally scaled by a per-read weight; reads are
    accumulated in index order so float summation is identical across
    backends.  The wildcard is dropped whenever any other candidate exists
    (parity with ``/root/reference/src/consensus.rs:540-564``).
    """
    votes: Dict[int, float] = {}
    # plain-Python ints/floats: identical IEEE-double arithmetic to the
    # numpy scalar path, without per-element numpy boxing overhead
    occ = stats.occ.tolist()
    split = stats.split.tolist()
    syms = symtab.tolist()
    for r, total in enumerate(split):
        if total == 0:
            continue
        w = 1.0 if weights is None else weights[r]
        if w <= 0.0:
            continue
        for s, c in enumerate(occ[r]):
            if c:
                sym = syms[s]
                add = c / total if weights is None else w * c / total
                votes[sym] = votes.get(sym, 0.0) + add
    if wildcard is not None and len(votes) > 1:
        votes.pop(wildcard, None)
    return votes


class _Node:
    """A search node: a partial consensus plus its scorer branch.

    ``prefetch`` holds this node's speculatively-expanded children —
    ``(passing_symbols, {sym: [child_handle, child_stats]})`` — produced
    by a fused multi-node dispatch before the node was popped.  It is a
    pure cache: nomination is a deterministic function of ``stats``, so
    consuming it at pop time is bit-identical to expanding then."""

    __slots__ = ("consensus", "handle", "active", "offsets", "stats", "prefetch")

    def __init__(self, consensus, handle, active, offsets, stats):
        self.consensus: bytes = consensus
        self.handle: int = handle
        self.active: List[bool] = active
        self.offsets: List[Optional[int]] = offsets
        self.stats: BranchStats = stats
        self.prefetch = None

    def key(self) -> Tuple:
        # Active wavefront state is a deterministic function of
        # (read, consensus, offset), so this tuple is full-state identity.
        return (self.consensus, tuple(self.offsets))

    def total_cost(self, cost: ConsensusCost) -> int:
        return sum(
            cost.apply(int(e)) for e, a in zip(self.stats.eds, self.active) if a
        )

    def priority(self, cost: ConsensusCost) -> Tuple[int, int]:
        # max-queue: smaller cost wins, then longer consensus
        return (-self.total_cost(cost), len(self.consensus))


def _replay_consensus(scorer, specs) -> None:
    """Advance freshly rooted branches to their nodes' consensuses by
    replaying every column through the ordinary ``push`` seam, batched
    across nodes per column.

    Device backends keep a branch-internal consensus buffer that
    ``activate`` replays when catching a late read's wavefront up — a
    fresh root's buffer is empty, so a checkpoint restore must fill it
    *before* activating the node's reads or the catch-up is a no-op
    and every rebuilt wavefront scores zero.  No reads are tracked
    during the replay, so the pushes only extend the buffer; the
    subsequent ``activate`` catch-up then walks the same per-column
    step the live search used, which keeps the rebuild bit-identical
    on every backend.  ``specs`` is ``[(handle, consensus), ...]``."""
    longest = max((len(consensus) for _h, consensus in specs), default=0)
    for col in range(longest):
        scorer.push_many([
            (handle, consensus[: col + 1])
            for handle, consensus in specs if len(consensus) > col
        ])


class ConsensusDWFA:
    """Generates the single best consensus (or the tied set) for the added
    sequences."""

    def __init__(self, config: Optional[CdwfaConfig] = None) -> None:
        self.config = config if config is not None else CdwfaConfig()
        self.sequences: List[bytes] = []
        self.offsets: List[Optional[int]] = []
        self.alphabet: set = set()

    @classmethod
    def with_config(cls, config: CdwfaConfig) -> "ConsensusDWFA":
        return cls(config)

    def add_sequence(self, sequence: bytes) -> None:
        self.add_sequence_offset(sequence, None)

    def add_sequence_offset(
        self, sequence: bytes, last_offset: Optional[int]
    ) -> None:
        sequence = bytes(sequence)
        self.alphabet.update(sequence)
        if self.config.wildcard is not None:
            self.alphabet.discard(self.config.wildcard)
        self.sequences.append(sequence)
        self.offsets.append(last_offset)

    @property
    def consensus_cost(self) -> ConsensusCost:
        return self.config.consensus_cost

    # ------------------------------------------------------------------

    def consensus(self) -> List[Consensus]:
        """Run the least-cost-first search and return every tied-best
        consensus, lexicographically sorted.

        Wraps :meth:`_consensus_impl` in a ``search`` tracer span and
        publishes the structured :class:`SearchReport` as
        ``self.last_search_report`` (one-line summary logged at INFO
        when ``config.log_search_summary`` is set, else DEBUG).
        """
        return _reported_search(self, "single", self._consensus_impl)

    def _consensus_impl(self) -> List[Consensus]:
        """Search skeleton parity: ``/root/reference/src/consensus.rs:139-351``."""
        cfg = self.config
        cost = cfg.consensus_cost
        restore = getattr(self, "_restore_state", None)
        self._restore_state = None
        maximum_error = math.inf
        nodes_explored = 0
        nodes_ignored = 0
        peak_queue_size = 0
        farthest_consensus = 0
        last_constraint = 0

        offsets = shift_offsets(self.offsets, cfg.auto_shift_offsets)
        logger.debug("Offsets: %s", offsets)

        # lengths at which late reads activate
        activate_points: Dict[int, List[int]] = {}
        max_activate = 0
        initially_active = 0
        for seq_index, offset in enumerate(offsets):
            if offset is not None:
                activate_length = offset + cfg.offset_compare_length
                activate_points.setdefault(activate_length, []).append(seq_index)
                max_activate = max(max_activate, activate_length)
            else:
                initially_active += 1
        if initially_active == 0:
            raise EngineError(
                "Must have at least one initial offset of None to see the consensus."
            )

        scorer = make_scorer(self.sequences, cfg)
        self._max_sequence_len = max(len(s) for s in self.sequences)
        tracker = PQueueTracker(
            self._max_sequence_len, cfg.max_capacity_per_size
        )
        pqueue = SetPriorityQueue()

        results: List[Consensus] = []
        pops = 0
        if restore is None:
            active = [o is None for o in offsets]
            root_handle = scorer.root(np.array(active, dtype=bool))
            root = _Node(
                b"",
                root_handle,
                active,
                [0 if a else None for a in active],
                scorer.stats(root_handle, b""),
            )
            tracker.insert(0)
            pqueue.push(root.key(), root, root.priority(cost))
        else:
            (maximum_error, nodes_explored, nodes_ignored, peak_queue_size,
             farthest_consensus, last_constraint, pops, results) = (
                self._restore_search(restore, scorer, pqueue, tracker, cost)
            )
        frontier = FrontierSampler("single")
        speculator = FrontierSpeculator(scorer, cfg)
        #: decision audit sink (``None`` when WAFFLE_AUDIT is off — the
        #: zero-overhead decision, made once per search)
        audit = obs_audit.search_sink("single")

        ctrl = ckpt_mod.current_controller()

        def _ckpt_body() -> Dict:
            # a closure over the loop locals: reads their values at
            # snapshot time, always at the top-of-pop-loop boundary
            return self._checkpoint_body(
                pqueue, tracker,
                maximum_error=maximum_error,
                nodes_explored=nodes_explored,
                nodes_ignored=nodes_ignored,
                peak_queue_size=peak_queue_size,
                farthest_consensus=farthest_consensus,
                last_constraint=last_constraint,
                pops=pops,
                results=results,
            )

        while not pqueue.is_empty():
            if ctrl is not None:
                try:
                    ctrl.poll(pops, _ckpt_body)
                finally:
                    self._last_checkpoint = ctrl.last_checkpoint
            peak_queue_size = max(peak_queue_size, len(pqueue))

            while (
                len(tracker) > cfg.max_queue_size
                or last_constraint >= cfg.max_nodes_wo_constraint
            ) and tracker.threshold() < farthest_consensus:
                tracker.increment_threshold()
                last_constraint = 0

            node, priority = pqueue.pop()
            pops += 1
            if pops % PROGRESS_LOG_INTERVAL == 0:
                logger.debug(
                    "search progress: %d pops, queue=%d, farthest=%d, "
                    "best_cost=%d", pops, len(pqueue), farthest_consensus,
                    -priority[0],
                )
                if obs_metrics.metrics_enabled():
                    obs_metrics.registry().gauge(
                        "waffle_search_queue_depth", engine="single"
                    ).set(len(pqueue))
            next_prio = pqueue.peek_priority()
            # per-pop adaptive-width tick: the policy sees every pop's
            # frontier (depth, best-vs-next gap), not only run-engage
            # pops, so sampled gang_width tracks the frontier shape and
            # cooldowns expire in real pops.  Pure policy — any value
            # is byte-safe; gangs only launch on the engage path below.
            gang_w = speculator.width(
                len(pqueue),
                (-next_prio[0]) - (-priority[0])
                if next_prio is not None else None,
            )
            if frontier.due(pops):
                frontier.sample(
                    pops, len(pqueue), len(tracker), -priority[0],
                    -next_prio[0] if next_prio is not None else None,
                    len(node.consensus), farthest_consensus,
                    counters=getattr(scorer, "counters", None),
                    gang_width=gang_w,
                )
            top_cost = -priority[0]
            top_len = len(node.consensus)
            tracker.remove(top_len)
            if audit is not None:
                # node identity digests: host bytes/flags the engine
                # already owns (WL002: nothing new is fetched)
                a_dig = obs_audit.crc_bytes(node.consensus)
                a_act = obs_audit.active_digest(
                    i for i, a in enumerate(node.active) if a
                )

            if (
                top_cost > maximum_error
                or top_len < tracker.threshold()
                or tracker.at_capacity(top_len)
            ):
                nodes_ignored += 1
                if audit is not None:
                    audit.emit({
                        "kind": "ignored", "pop": pops, "len": top_len,
                        "dig": a_dig, "act": a_act, "prio": top_cost,
                    })
                self._drop_prefetch(scorer, node)
                scorer.free(node.handle)
                continue

            # -- device fast path: extend the popped node through
            # unambiguous stretches on device (one host round-trip per
            # event instead of per base), then replay the per-length
            # bookkeeping exactly.  The run continues while the node keeps
            # winning pops ((-cost, len) priority vs the best other queued
            # entry; full ties lose to the earlier insert) and only
            # engages when this pop's own nomination is a single candidate
            # — otherwise step 0 would stop immediately.  max_steps is
            # bounded by an exact host simulation of the threshold /
            # capacity bookkeeping, so the run may start behind the
            # farthest frontier without replaying a step the real search
            # would have pruned.
            fp = fast_paths(scorer)
            # MEGASTEP preference: when the scorer exposes run_mega
            # (WAFFLE_MEGASTEP on a device backend), the pop loop
            # becomes the SPILL path — one engagement swallows an
            # entire unambiguous stretch under a single bundled
            # round trip, and this host loop only arbitrates the
            # genuine events (forks, reached ends, pop losses, band
            # growth, budget caps).  Same call contract, bit-identical
            # results, so everything downstream is unchanged.
            run_extend = (
                fp.run_mega if fp.run_mega is not None else fp.run_extend
            )
            reached_now = self._reached_end(node, cfg.allow_early_termination)
            force_sym = -1
            if run_extend is not None:
                passing_now = (
                    node.prefetch[0]
                    if node.prefetch is not None
                    else self._nominate(scorer, node)
                )
                # -- arena fast path: resolve the pop competition among
                # the in-hand node and the next-best queue entries on
                # device (see DualConsensusDWFA._arena_attempt).  The
                # arena has no record absorption, so reached nodes skip
                # it (its step 0 would stop code 2)
                if (
                    not reached_now
                    and (
                        len(passing_now) == 1
                        or 2
                        <= len(passing_now)
                        <= fp.arena_cre_per_event
                    )
                    and fp.run_arena is not None
                    # under lockstep shadow the arena's opaque subtree
                    # absorption would hide per-pop decisions from the
                    # comparator; strict alignment skips it (byte-safe:
                    # the arena is a pure fast path)
                    and not (audit is not None and audit.strict_align)
                    # a pending frontier-gang deposit is this pop's run
                    # already paid for; the arena would drop it unspent
                    and not speculator.pending(node.handle)
                ):
                    arena = self._arena_attempt(
                        scorer, pqueue, node, maximum_error,
                        activate_points, cost, tracker,
                        farthest_consensus, last_constraint,
                    )
                    if arena is not None:
                        (farthest_consensus, last_constraint,
                         arena_explored, arena_ignored) = arena
                        nodes_explored += arena_explored
                        nodes_ignored += arena_ignored
                        if audit is not None:
                            audit.emit({
                                "kind": "arena", "pop": pops,
                                "len": top_len, "dig": a_dig,
                                "act": a_act, "prio": top_cost,
                                "explored": arena_explored,
                                "ignored": arena_ignored,
                            })
                        continue
                best_other = pqueue.peek_priority()
                other_cost = 2**31 - 1
                other_len = 0
                if best_other is not None:
                    other_cost = -best_other[0]
                    other_len = best_other[1]
                if (
                    len(passing_now) == 1
                    and not reached_now
                    and len(scorer.symtab) > 1
                    and faults_mod.maybe_flip_vote(cfg.backend, top_len)
                ):
                    # injected wrong *decision* (``flip_vote`` fault):
                    # silently commit a different alphabet symbol than
                    # the nomination voted for — invisible to dispatch
                    # validation, catchable only by the audit plane
                    self._drop_prefetch(scorer, node)
                    wrong = (
                        scorer.sym_id[passing_now[0]] + 1
                    ) % len(scorer.symtab)
                    passing_now = [int(scorer.symtab[wrong])]
                # -- forced-child fold: with exactly one passing symbol
                # and no prefetched children, the expand path's outcome
                # is fully known host-side (one child = consensus + sym,
                # created unconditionally), so the run call pushes it as
                # its forced step 0 — replacing the separate clone+push
                # dispatches — and simply stops there if the child would
                # lose the next pop (the kernel re-queues it, exactly
                # like the expand path's queue insert).  A near-tie vote
                # that would stop an unforced run at step 0 commits the
                # identical symbol here: the host's f64 nomination IS
                # the ground truth the kernel's EPS contract defers to.
                if (
                    len(passing_now) == 1
                    and node.prefetch is None
                    and not reached_now
                ):
                    # (a reached pop must evaluate its record through the
                    # kernel's loop checks, so it is never forced)
                    force_sym = int(scorer.sym_id[passing_now[0]])
                engage = len(passing_now) == 1 and (
                    force_sym >= 0
                    or top_cost < other_cost
                    or (top_cost == other_cost and top_len > other_len)
                )
            else:
                engage = False
            if engage:
                next_act = min(
                    (l for l in activate_points if l > top_len), default=None
                )
                max_steps = min(self._max_sequence_len * 2 + 256, RUN_SIM_CAP)
                if next_act is not None:
                    max_steps = min(max_steps, next_act - top_len - 1)
                if max_steps >= 1:
                    max_steps = tracker.simulate_run_bound(
                        top_len,
                        farthest_consensus,
                        last_constraint,
                        cfg.max_queue_size,
                        cfg.max_nodes_wo_constraint,
                        max_steps,
                    )
                if max_steps >= 1:
                    me_budget = (
                        int(maximum_error)
                        if maximum_error != math.inf
                        else 2**31 - 1
                    )
                    # -- frontier-parallel speculation: alongside this
                    # run, advance the next-best queued branches through
                    # one ragged gang dispatch; their results wait as
                    # consume-once deposits for their own pops
                    if gang_w > 1:
                        self._gang_attempt(
                            speculator, scorer, pqueue, node, gang_w,
                            me_budget, other_cost, other_len, max_steps,
                            force_sym, maximum_error,
                            cost is ConsensusCost.L2_DISTANCE,
                        )
                    steps, _code, appended, run_stats, records = run_extend(
                        node.handle,
                        node.consensus,
                        me_budget,
                        other_cost,
                        other_len,
                        cfg.min_count,
                        cost is ConsensusCost.L2_DISTANCE,
                        max_steps,
                        first_sym=force_sym,
                        # under early termination the host's require-all
                        # record condition can never hold while a read
                        # is not yet activated, but the kernel's
                        # conservative fold would buffer bogus records
                        allow_records=(
                            not cfg.allow_early_termination
                            or all(node.active)
                        ),
                    )
                    # replay absorbed reached-state records in commit
                    # order, exactly as the completion path would have at
                    # each pop (the stopped state is NOT in the buffer —
                    # its own pop records it below)
                    for rec_j, rec_fin in records:
                        if not all(node.active):
                            scorer.free(node.handle)
                            raise EngineError(
                                "Finalize called on DWFA that was never initialized."
                            )
                        rec_scores = [cost.apply(int(v)) for v in rec_fin]
                        maximum_error = accept_record(
                            maximum_error,
                            results,
                            sum(rec_scores),
                            Consensus(
                                node.consensus + appended[:rec_j],
                                cost,
                                rec_scores,
                            ),
                            cfg.max_return_size,
                        )
                    # the snapshot matches the stopped position whether
                    # or not steps committed (steps == 0 leaves state
                    # as-is), so adopt it either way — its fin field
                    # saves the finalize dispatch at a reached-end pop
                    node.stats = run_stats
                    if audit is not None and steps > 0:
                        audit.emit({
                            "kind": "run", "pop": pops, "len": top_len,
                            "dig": a_dig, "act": a_act, "prio": top_cost,
                            "via": (
                                "mega" if fp.run_mega is not None
                                else "run"
                            ),
                            "code": int(_code),
                            "forced": force_sym >= 0,
                            "syms": obs_audit.b64(appended),
                            "finals": [int(rj) for rj, _ in records],
                            "tail": obs_audit.tail(
                                node.consensus + appended
                            ),
                        })
                    if steps > 0:
                        # the branch advanced past the prefetched children
                        self._drop_prefetch(scorer, node)
                        farthest_consensus, last_constraint = (
                            replay_run_bookkeeping(
                                tracker,
                                cfg,
                                top_len,
                                steps,
                                farthest_consensus,
                                last_constraint,
                            )
                        )
                        nodes_explored += steps
                        node.consensus = node.consensus + appended
                        if not pqueue.push(
                            node.key(), node, node.priority(cost)
                        ):  # pragma: no cover - chain nodes are unique
                            tracker.remove(len(node.consensus))
                            scorer.free(node.handle)
                        continue

            farthest_consensus = max(farthest_consensus, top_len)
            nodes_explored += 1
            last_constraint += 1
            tracker.process(top_len)

            # -- result check: any (or, with early termination, all) read
            # touching its baseline end means this consensus may be complete
            # (reached_now is current: every path that changed node.stats
            # since it was computed has already `continue`d)
            if reached_now:
                if not all(node.active):
                    scorer.free(node.handle)
                    raise EngineError(
                        "Finalize called on DWFA that was never initialized."
                    )
                fin_eds = (
                    node.stats.fin
                    if node.stats.fin is not None
                    else scorer.finalized_eds(node.handle, node.consensus)
                )
                fin_scores = [cost.apply(int(e)) for e in fin_eds]
                maximum_error = accept_record(
                    maximum_error,
                    results,
                    sum(fin_scores),
                    Consensus(node.consensus, cost, fin_scores),
                    cfg.max_return_size,
                )
                if audit is not None:
                    audit.emit({
                        "kind": "final", "pop": pops, "len": top_len,
                        "dig": a_dig, "act": a_act,
                        "score": sum(fin_scores),
                    })

            # -- nominate + expand (with frontier-synchronous batching:
            # the popped node's children and the next best queued nodes'
            # children go through ONE fused clone+push dispatch, consumed
            # bit-identically when those nodes are popped)
            if node.prefetch is None:
                peers = [
                    n
                    for n, _p in pqueue.peek_top(cfg.prefetch_width - 1)
                    # a pending gang deposit is consumed by a FORCED pop;
                    # prefetching the peer would unforce it (see
                    # _gang_attempt), wasting the speculated run
                    if n.prefetch is None
                    and not speculator.pending(n.handle)
                ]
                self._prefetch_expansions(
                    scorer, [node] + peers, in_place_first=True
                )
            passing, expansion = node.prefetch
            node.prefetch = None
            if audit is not None:
                audit.emit({
                    "kind": "branch", "pop": pops, "len": top_len,
                    "dig": a_dig, "act": a_act, "prio": top_cost,
                    "syms": obs_audit.b64(bytes(sorted(passing))),
                    "tail": obs_audit.tail(node.consensus),
                })

            new_nodes: List[_Node] = []
            if not passing:
                if top_len < max_activate:
                    scorer.free(node.handle)
                    raise EngineError(
                        f"Encountered coverage gap: consensus is length {top_len} "
                        f"with no candidates, but sequences activate at {max_activate}"
                    )
                scorer.free(node.handle)
                # otherwise: dead end past all activations, drop the branch
            else:
                for sym in passing:
                    handle, stats = expansion[sym]
                    new_nodes.append(
                        _Node(
                            node.consensus + bytes([sym]),
                            handle,
                            list(node.active),
                            list(node.offsets),
                            stats,
                        )
                    )
                if all(c.handle != node.handle for c in new_nodes):
                    scorer.free(node.handle)

            for child in new_nodes:
                activate_list = activate_points.get(len(child.consensus))
                if activate_list:
                    for seq_index in activate_list:
                        self._activate(scorer, child, seq_index)
                    child.stats = scorer.stats(child.handle, child.consensus)
                tracker.insert(len(child.consensus))
                if not pqueue.push(child.key(), child, child.priority(cost)):
                    # identical node already queued (cannot normally happen:
                    # a consensus string uniquely identifies its path)
                    logger.warning("duplicate search node %r", child.consensus)
                    tracker.remove(len(child.consensus))
                    scorer.free(child.handle)

        check_invariant(len(tracker) == 0, "tracker drained at search end")

        results.sort(key=lambda c: c.sequence)
        #: search-shape observability for bench.py / profiling; the
        #: public ``consensus()`` wrapper turns this into a SearchReport
        self.last_search_stats = {
            "nodes_explored": nodes_explored,
            "nodes_ignored": nodes_ignored,
            "peak_queue_size": peak_queue_size,
            "scorer_counters": dict(getattr(scorer, "counters", {})),
            "backend": getattr(scorer, "timed_backend", None)
            or getattr(scorer, "backend", None) or cfg.backend,
        }
        from waffle_con_tpu.runtime.watchdog import enforce_dispatch_budget

        enforce_dispatch_budget(
            cfg, self.last_search_stats["scorer_counters"], "single"
        )
        return results

    # -- checkpoint / resume -------------------------------------------

    def snapshot(self) -> Optional["ckpt_mod.SearchCheckpoint"]:
        """The most recent :class:`SearchCheckpoint` built for this
        engine's search (by the installed
        :class:`~waffle_con_tpu.models.checkpoint.CheckpointController`),
        or ``None`` — survives a preempted/expired search."""
        return getattr(self, "_last_checkpoint", None)

    def _checkpoint_body(
        self, pqueue, tracker, *, maximum_error, nodes_explored,
        nodes_ignored, peak_queue_size, farthest_consensus,
        last_constraint, pops, results,
    ) -> Dict:
        """JSON checkpoint body at a pop boundary.  Only host-level node
        identity travels (consensus bytes, active sets, offsets) — never
        scorer handles or wavefront arrays; prefetch caches and
        frontier-gang deposits are deliberately absent (dropping them is
        byte-safe: they are pure caches / consume-once speculation)."""
        entries = []
        for _key, nd, pri, seq in pqueue.export_entries():
            entries.append({
                "consensus": ckpt_mod.b64(nd.consensus),
                "active": [1 if a else 0 for a in nd.active],
                "offsets": [o if o is None else int(o)
                            for o in nd.offsets],
                "priority": [int(p) for p in pri],
                "seq": int(seq),
            })
        return {
            "kind": "single",
            "config": ckpt_mod.encode_config_dict(self.config),
            "reads": [ckpt_mod.b64(s) for s in self.sequences],
            "offsets": [o if o is None else int(o) for o in self.offsets],
            "state": {
                "entries": entries,
                "queue_seq": pqueue.export_seq(),
                "tracker": tracker.export_state(),
                "maximum_error": (None if maximum_error == math.inf
                                  else int(maximum_error)),
                "nodes_explored": int(nodes_explored),
                "nodes_ignored": int(nodes_ignored),
                "peak_queue_size": int(peak_queue_size),
                "farthest_consensus": int(farthest_consensus),
                "last_constraint": int(last_constraint),
                "pops": int(pops),
                "results": [
                    {"sequence": ckpt_mod.b64(c.sequence),
                     "scores": [int(s) for s in c.scores]}
                    for c in results
                ],
            },
        }

    def _restore_search(self, restore, scorer, pqueue, tracker, cost):
        """Rebuild the mid-search state captured by
        :meth:`_checkpoint_body` and return the loop-local tuple.

        Each branch is rebuilt through the ordinary dispatch seam —
        fresh ``root``, the node's consensus replayed column-by-column
        through ``push`` (see :func:`_replay_consensus`), then one
        ``activate`` per active read — which is bit-identical on any
        backend because active wavefront state is a deterministic
        function of ``(read, consensus, offset)`` and ``activate``'s
        catch-up walks the same per-column step the live search used
        (late activation behind the frontier is an ordinary mid-search
        event).  The stored priorities double as an integrity check: a
        rebuilt node whose priority disagrees with the checkpoint means
        the checkpoint does not belong to these reads/config, and the
        restore is rejected rather than silently corrupting the
        search."""
        st = restore["state"]
        cost_local = cost
        extra = int(restore.get("extra", 0))
        n_total = len(self.sequences)
        n_base = n_total - extra
        try:
            if not extra:
                tracker.restore_state(st["tracker"])
            results = [
                Consensus(ckpt_mod.unb64(r["sequence"]), cost_local,
                          [int(s) for s in r["scores"]])
                for r in st["results"]
            ]
            maximum_error = (math.inf if st["maximum_error"] is None
                             else int(st["maximum_error"]))
            staged = []
            for entry in st["entries"]:
                consensus = ckpt_mod.unb64(entry["consensus"])
                active = [bool(a) for a in entry["active"]]
                offs = [o if o is None else int(o)
                        for o in entry["offsets"]]
                if len(active) != n_base or len(offs) != n_base:
                    raise ckpt_mod.CheckpointRejected(
                        "node read-count mismatch vs checkpoint reads"
                    )
                # incremental reads join every live branch at offset 0
                active += [True] * extra
                offs += [0] * extra
                handle = scorer.root(np.zeros(n_total, dtype=bool))
                staged.append((entry, consensus, active, offs, handle))
            _replay_consensus(
                scorer, [(handle, consensus)
                         for _e, consensus, _a, _o, handle in staged]
            )
            for entry, consensus, active, offs, handle in staged:
                for read_index, is_active in enumerate(active):
                    if is_active:
                        scorer.activate(
                            handle, read_index, offs[read_index], consensus
                        )
                node = _Node(
                    consensus, handle, active, offs,
                    scorer.stats(handle, consensus),
                )
                prio = node.priority(cost_local)
                if not extra and tuple(int(p) for p in prio) != tuple(
                    int(p) for p in entry["priority"]
                ):
                    raise ckpt_mod.CheckpointRejected(
                        "restored node priority mismatch — checkpoint "
                        "does not match its reads/config"
                    )
                if extra:
                    tracker.insert(len(consensus))
                pqueue.push_restored(
                    node.key(), node, prio, int(entry["seq"])
                )
            pqueue.restore_seq(int(st["queue_seq"]))
            if extra:
                # the wider read set invalidates the accepted results
                # and the cost bound; the search re-derives both
                results = []
                maximum_error = math.inf
            return (
                maximum_error,
                int(st["nodes_explored"]),
                int(st["nodes_ignored"]),
                int(st["peak_queue_size"]),
                int(st["farthest_consensus"]),
                int(st["last_constraint"]),
                int(st["pops"]),
                results,
            )
        except ckpt_mod.CheckpointError:
            raise
        except (KeyError, TypeError, ValueError, IndexError) as exc:
            raise ckpt_mod.CheckpointRejected(
                f"malformed single-engine checkpoint state: {exc}"
            ) from None

    @classmethod
    def resume(
        cls, checkpoint, extra_reads: Sequence[bytes] = ()
    ) -> "ConsensusDWFA":
        """An engine primed to continue ``checkpoint`` (a
        :class:`SearchCheckpoint` or its wire-dict form); run
        :meth:`consensus` on it to finish the search.  ``extra_reads``
        join every live branch initially-active at offset 0 —
        incremental (streaming) resume; with no extras the resumed
        search is byte-identical to the uninterrupted one."""
        body = ckpt_mod.resume_body(checkpoint, "single")
        try:
            config = ckpt_mod.decode_config_dict(body["config"])
            reads = [ckpt_mod.unb64(r) for r in body["reads"]]
            offsets = [o if o is None else int(o)
                       for o in body["offsets"]]
            state = body["state"]
            if not isinstance(state, dict) or len(reads) != len(offsets):
                raise ckpt_mod.CheckpointRejected(
                    "malformed single-engine checkpoint body"
                )
        except ckpt_mod.CheckpointError:
            raise
        except (KeyError, TypeError, ValueError) as exc:
            raise ckpt_mod.CheckpointRejected(
                f"malformed single-engine checkpoint body: {exc}"
            ) from None
        engine = cls(config)
        for read, offset in zip(reads, offsets):
            engine.add_sequence_offset(read, offset)
        extras = [bytes(r) for r in extra_reads]
        for read in extras:
            engine.add_sequence(read)
        engine._restore_state = {"state": state, "extra": len(extras)}
        return engine

    # ------------------------------------------------------------------

    def _arena_attempt(
        self, scorer, pqueue, node, maximum_error, activate_points, cost,
        tracker, farthest_consensus, last_constraint,
    ):
        """Single-engine device pop arena (dual twin:
        ``DualConsensusDWFA._arena_attempt``): the in-hand node plus up
        to ``ARENA_K - 1`` next-best queue entries extend on device under
        the exact pop/tracker semantics.  Returns ``None`` when not
        engaged (competitors restored with their original insertion
        order), else ``(farthest_consensus, last_constraint, steps)``."""
        cfg = self.config
        if pqueue.is_empty():
            return None  # no competitor: the plain run path is strictly better
        fp = fast_paths(scorer)
        taken = []
        take_max = fp.arena_take_max
        while len(taken) < take_max and not pqueue.is_empty():
            taken.append(pqueue.pop_with_seq())
        nodes = [node] + [t[0] for t in taken]

        def restore_all():
            for cand, pri, seq in taken:
                pqueue.push_restored(cand.key(), cand, pri, seq)

        step_limit = fp.arena_cap
        for nd in nodes:
            nl = len(nd.consensus)
            next_act = min((l for l in activate_points if l > nl), default=None)
            if next_act is not None:
                step_limit = min(step_limit, next_act - nl - 1)
        if step_limit < 1:
            restore_all()
            return None

        rest = pqueue.peek_priority()
        rest_cost = 2**31 - 1
        rest_len = 0
        if rest is not None:
            rest_cost = -rest[0]
            rest_len = rest[1]

        needed = (
            max(
                max(len(nd.consensus) for nd in nodes),
                farthest_consensus,
            )
            + fp.arena_cap
            + 4
        )
        win_len = 1 << (needed - 1).bit_length()
        lc, pc = tracker.export_windows(win_len)
        zeros = np.zeros(win_len, dtype=np.int32)
        tr_scalars = [
            [
                tracker.threshold(), len(tracker),
                farthest_consensus, last_constraint,
            ],
            [0, 0, 0, 0],  # no second node kind in the single engine
        ]
        me_budget = (
            int(maximum_error) if maximum_error != math.inf else 2**31 - 1
        )
        (events, nsteps, _code, _stop_node, node_steps, appended,
         sides_stats, _sides_act, alive, creations) = fp.run_arena(
            [(nd.handle, None, len(nd.consensus), 0) for nd in nodes],
            me_budget,
            cfg.min_count,
            0,
            0,
            cost is ConsensusCost.L2_DISTANCE,
            False,
            rest_cost,
            rest_len,
            cfg.max_queue_size,
            cfg.max_capacity_per_size,
            step_limit,
            cfg.max_nodes_wo_constraint,
            np.stack([lc, zeros]),
            np.stack([pc, zeros]),
            np.asarray(tr_scalars, dtype=np.int32),
            create_mode=1,  # singles only: this engine has no dual nodes
        )
        if nsteps == 0:
            restore_all()
            return None

        n_live = len(nodes)
        for i, nd in enumerate(nodes):
            if node_steps[i] > 0 or not alive[i]:
                self._drop_prefetch(scorer, nd)

        # exact tracker replay of the committed interleaved pop sequence
        # (lens grows as on-device-created children are registered)
        lens = [len(nd.consensus) for nd in nodes]
        far = [farthest_consensus]
        lcon = [last_constraint]
        replay_arena_history(
            events, lens, [0] * len(nodes), [tracker], far, lcon, cfg,
            creations=creations,
        )

        # apply extensions to the original nodes first (a split-consumed
        # parent keeps its committed prefix so children can build on it)
        for i, nd in enumerate(nodes):
            if node_steps[i] == 0:
                continue
            nd.consensus = nd.consensus + appended[2 * i]
            nd.stats = sides_stats[2 * i]

        # materialize on-device-created children (mode 1: one single
        # child per passing symbol of the consumed parent)
        all_nodes = list(nodes)
        for j, cre in enumerate(creations):
            idx = n_live + j
            parent = all_nodes[cre["parent"]]
            child = _Node(
                parent.consensus[: cre["created_len"] - 1]
                + bytes([cre["sym1"]])
                + appended[2 * idx],
                cre["h1"],
                list(parent.active),
                list(parent.offsets),
                sides_stats[2 * idx],
            )
            all_nodes.append(child)

        def on_duplicate(_idx, nd):
            # converged to an existing key: drop the newcomer and undo
            # its replayed tracker insert (cf. the expansion path)
            logger.warning("duplicate search node (arena re-queue)")
            tracker.remove(len(nd.consensus))
            scorer.free(nd.handle)

        requeue_arena_nodes(
            pqueue, all_nodes, taken, node_steps, events, cost,
            on_duplicate, alive=alive, n_live=n_live,
        )
        for i, nd in enumerate(all_nodes):
            if not alive[i]:
                scorer.free(nd.handle)
        explored = sum(
            1 for k, _ in events if k in ("commit", "split")
        )
        ignored = sum(1 for k, _ in events if k == "discard")
        return far[0], lcon[0], explored, ignored

    def _gang_attempt(
        self,
        speculator: FrontierSpeculator,
        scorer: WavefrontScorer,
        pqueue: SetPriorityQueue,
        node: _Node,
        gang_w: int,
        me_budget: int,
        other_cost: int,
        other_len: int,
        max_steps: int,
        force_sym: int,
        maximum_error: float,
        l2: bool,
    ) -> None:
        """Frontier-parallel speculation: gang the in-hand node's run
        with the next-best queued branches through one ragged dispatch.

        The in-hand member carries its real call arguments (its deposit
        is consumed by the ``run_extend`` immediately following).  Peers
        are chosen so their own future pop will make the *forced* call
        the speculation assumes: un-prefetched, un-reached, exactly one
        passing symbol — the same ``_nominate`` the pop will evaluate,
        so the forced symbol matches by determinism.  Their other-branch
        (cost, len) is predicted from the entry peeked behind them; any
        misprediction is caught by consumption validation, so peer
        selection is pure commit-rate tuning, never a correctness
        concern."""
        cfg = self.config
        members: List[GangMember] = []
        if not speculator.pending(node.handle):
            members.append(GangMember(
                node.handle, node.consensus, me_budget, other_cost,
                other_len, max_steps, force_sym,
            ))
        peeked = pqueue.peek_top(gang_w)
        for i, (pn, pprio) in enumerate(peeked):
            if len(members) >= gang_w:
                break
            if -pprio[0] > maximum_error:
                continue  # its pop will be ignored, not run
            if pn.prefetch is not None or speculator.pending(pn.handle):
                continue
            if self._reached_end(pn, cfg.allow_early_termination):
                continue  # a reached pop is never forced
            passing = self._nominate(scorer, pn)
            if len(passing) != 1:
                continue
            if i + 1 < len(peeked):
                nxt = peeked[i + 1][1]
                poc, pol = -nxt[0], nxt[1]
            else:
                poc, pol = 2**31 - 1, 0
            members.append(GangMember(
                pn.handle, pn.consensus, me_budget, poc, pol,
                max_steps, int(scorer.sym_id[passing[0]]),
            ))
        if len(members) >= 2:
            speculator.gang(members, cfg.min_count, l2)

    def _nominate(self, scorer: WavefrontScorer, node: _Node) -> List[int]:
        """Passing extension symbols for a node — a pure function of its
        stats (so it can run at prefetch time with an identical result)."""
        cfg = self.config
        candidates = candidates_from_stats(
            node.stats, scorer.symtab, cfg.wildcard
        )
        max_observed = max(candidates.values(), default=float(cfg.min_count))
        active_threshold = min(float(cfg.min_count), max_observed)
        return sorted(
            sym for sym, count in candidates.items() if count >= active_threshold
        )

    def _prefetch_expansions(
        self,
        scorer: WavefrontScorer,
        nodes: List[_Node],
        in_place_first: bool = False,
    ) -> None:
        """Expand every listed node's children in one fused clone dispatch
        plus one fused push dispatch, storing the results on the nodes.

        ``in_place_first``: when the FIRST node has exactly one passing
        symbol, push its sole child onto the parent's own branch slot
        instead of a clone — exact because the parent is the in-hand pop,
        consumed and freed in this same iteration (never valid for peers,
        whose pristine state is still needed at their own pop)."""
        per_node_passing = [self._nominate(scorer, n) for n in nodes]
        clone_push = fast_paths(scorer).clone_push_many
        if clone_push is not None:
            specs: List[Tuple[int, bytes, bool]] = []
            slots: List[List] = []
            for i, (node, passing) in enumerate(
                zip(nodes, per_node_passing)
            ):
                expansion = {}
                reuse = in_place_first and i == 0 and len(passing) == 1
                for sym in passing:
                    entry = [None, None]
                    expansion[sym] = entry
                    specs.append(
                        (node.handle, node.consensus + bytes([sym]), reuse)
                    )
                    slots.append(entry)
                node.prefetch = (passing, expansion)
            for entry, (handle, stats) in zip(slots, clone_push(specs)):
                entry[0] = handle
                entry[1] = stats
            return
        clone_srcs: List[int] = []
        for i, (node, passing) in enumerate(zip(nodes, per_node_passing)):
            if not (in_place_first and i == 0 and len(passing) == 1):
                clone_srcs.extend([node.handle] * len(passing))
        handles = scorer.clone_many(clone_srcs)
        push_specs: List[Tuple[int, bytes]] = []
        slots = []
        hi = 0
        for i, (node, passing) in enumerate(zip(nodes, per_node_passing)):
            expansion = {}
            reuse = in_place_first and i == 0 and len(passing) == 1
            for sym in passing:
                if reuse:
                    handle = node.handle
                else:
                    handle = handles[hi]
                    hi += 1
                entry = [handle, None]
                expansion[sym] = entry
                push_specs.append((handle, node.consensus + bytes([sym])))
                slots.append(entry)
            node.prefetch = (passing, expansion)
        for entry, stats in zip(slots, scorer.push_many(push_specs)):
            entry[1] = stats

    def _drop_prefetch(self, scorer: WavefrontScorer, node: _Node) -> None:
        if node.prefetch is not None:
            for handle, _stats in node.prefetch[1].values():
                scorer.free(handle)
            node.prefetch = None

    def _reached_end(self, node: _Node, require_all: bool) -> bool:
        flags = [
            bool(r) if a else False
            for r, a in zip(node.stats.reached, node.active)
        ]
        return all(flags) if require_all else any(flags)

    def _activate(
        self, scorer: WavefrontScorer, node: _Node, seq_index: int
    ) -> None:
        check_invariant(not node.active[seq_index], "activating an already-active read")
        cfg = self.config
        offset = scorer.best_activation_offset(
            node.consensus,
            seq_index,
            cfg.offset_window,
            cfg.offset_compare_length,
            cfg.wildcard,
        )
        scorer.activate(node.handle, seq_index, offset, node.consensus)
        node.active[seq_index] = True
        node.offsets[seq_index] = offset
