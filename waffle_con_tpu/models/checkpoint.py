"""Serializable search checkpoints: snapshot/resume for the engines.

A :class:`SearchCheckpoint` captures a consensus search at a *pop
boundary* — the top of the engine's pop loop, where no speculative
state is in flight — as plain JSON types: the priority queue's entries
(consensus bytes, active sets, offsets, priorities, insertion seqs),
the :class:`~waffle_con_tpu.utils.pqueue.PQueueTracker` histograms, the
loop counters, and the accepted results so far.

**What is deliberately NOT serialized**: scorer handles, wavefront
arrays, prefetch caches, frontier-gang deposits, and adaptive-M policy
state.  Active wavefront state is a deterministic function of
``(read, consensus, offset)`` (the engines' node-identity invariant),
so resume rebuilds every branch with one ``root`` + per-read
``activate`` through the ordinary dispatch seam and gets bit-identical
state on any backend.  Prefetch/gang deposits are pure caches and
consume-once speculations whose absence is byte-safe by construction —
dropping them at snapshot can change *when* work happens, never what
the search returns.  That is what makes a resumed search
byte-identical-by-construction to an uninterrupted one.

Integrity: the wire form carries a CRC32 over the canonical body JSON
plus a version byte; truncated, bit-flipped, or version-skewed
checkpoints raise the typed :class:`CheckpointRejected` (callers
degrade to restart-from-scratch, never hang).  Each restored node's
stored priority is additionally re-derived from its rebuilt stats — a
checkpoint that does not match its own reads/config is rejected at
restore time rather than silently corrupting the search.

The :class:`CheckpointController` is the engines' polling seam: the
serve layer installs one per job (thread-local, mirroring the scorer
decorator idiom) and the engines call :meth:`CheckpointController.poll`
once per pop.  The controller decides when to snapshot (periodic
interval, explicit request, deadline lapse, or pinned test pops) and
what to do with it (deliver to a callback, attach to the raised
deadline error, or preempt the search with :class:`SearchPreempted`).
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
import zlib
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

#: Checkpoint format version; a mismatch is a typed rejection, never a
#: best-effort parse.
CKPT_VERSION = 1

#: Engine kinds a checkpoint can describe.
CKPT_KINDS = ("single", "dual", "priority")


class CheckpointError(RuntimeError):
    """Base class for checkpoint failures."""


class CheckpointRejected(CheckpointError):
    """A checkpoint that must not be restored: corrupt payload, version
    skew, or state inconsistent with its own reads/config.  Callers
    degrade to restart-from-scratch."""


class SearchPreempted(RuntimeError):
    """A search stopped on purpose at a pop boundary, carrying its
    checkpoint (worker drain / preemptive migration)."""

    def __init__(self, checkpoint: "SearchCheckpoint") -> None:
        super().__init__("search preempted at a checkpoint boundary")
        self.checkpoint = checkpoint


# -- bytes-in-JSON helpers ---------------------------------------------

def b64(data: bytes) -> str:
    return base64.b64encode(bytes(data)).decode("ascii")


def unb64(text: str) -> bytes:
    try:
        return base64.b64decode(text.encode("ascii"), validate=True)
    except Exception as exc:
        raise CheckpointRejected(f"bad base64 field: {exc}") from None


def _canonical(body: Dict) -> bytes:
    """Canonical JSON bytes of the body (sorted keys) — what the CRC
    covers, independent of dict insertion order."""
    return json.dumps(
        body, sort_keys=True, separators=(",", ":"), allow_nan=False
    ).encode("utf-8")


class SearchCheckpoint:
    """One search snapshot: engine kind + JSON-typed body.

    The body always holds ``config`` (wire config codec), the engine's
    reads (``reads`` b64 list, or ``chains``/``seed_groups`` for the
    priority engine), ``offsets``, and an engine-specific ``state``
    dict.  Use :meth:`to_wire`/:meth:`from_wire` for the CRC'd plain-
    dict form that travels in frames, :meth:`to_json` for a string.
    """

    __slots__ = ("version", "kind", "body")

    def __init__(self, kind: str, body: Dict,
                 version: int = CKPT_VERSION) -> None:
        self.version = version
        self.kind = kind
        self.body = body

    def to_wire(self) -> Dict:
        """CRC'd plain-JSON-types form (never pickle)."""
        return {
            "version": self.version,
            "kind": self.kind,
            "body": self.body,
            "crc": zlib.crc32(_canonical(self.body)),
        }

    @classmethod
    def from_wire(cls, obj: Any) -> "SearchCheckpoint":
        """Validate and rebuild; raises :class:`CheckpointRejected` on
        any malformed, skewed, or corrupted payload."""
        if not isinstance(obj, dict):
            raise CheckpointRejected("checkpoint payload must be an object")
        version = obj.get("version")
        if version != CKPT_VERSION:
            raise CheckpointRejected(
                f"checkpoint version {version!r} (speaking {CKPT_VERSION})"
            )
        kind = obj.get("kind")
        if kind not in CKPT_KINDS:
            raise CheckpointRejected(f"unknown checkpoint kind {kind!r}")
        body = obj.get("body")
        if not isinstance(body, dict):
            raise CheckpointRejected("checkpoint body must be an object")
        try:
            crc = int(obj.get("crc"))
        except (TypeError, ValueError):
            raise CheckpointRejected("checkpoint crc missing") from None
        if zlib.crc32(_canonical(body)) != crc:
            raise CheckpointRejected("checkpoint body CRC mismatch")
        return cls(kind, body, version=version)

    def to_json(self) -> str:
        return json.dumps(self.to_wire(), separators=(",", ":"),
                          allow_nan=False)

    @classmethod
    def from_json(cls, text: str) -> "SearchCheckpoint":
        try:
            obj = json.loads(text)
        except (TypeError, ValueError) as exc:
            raise CheckpointRejected(
                f"undecodable checkpoint JSON: {exc}"
            ) from None
        return cls.from_wire(obj)

    def byte_size(self) -> int:
        """Serialized size in bytes (the wire JSON form)."""
        return len(self.to_json().encode("utf-8"))


# -- config codec (shared with the wire protocol) ----------------------
#
# Lazy imports: wire.py pulls the serve package; by the time an engine
# snapshots or resumes, the package import graph is long settled.

def encode_config_dict(config) -> Optional[Dict]:
    from waffle_con_tpu.serve.procs.wire import encode_config

    return encode_config(config)


def decode_config_dict(obj: Optional[Dict]):
    from waffle_con_tpu.serve.procs.wire import WireError, decode_config

    try:
        return decode_config(obj)
    except WireError as exc:
        raise CheckpointRejected(str(exc)) from None


def resume_body(checkpoint, kind: str) -> Dict:
    """Validate a checkpoint (or its wire-dict form) against the engine
    ``kind`` doing the resuming and hand back its body."""
    if not isinstance(checkpoint, SearchCheckpoint):
        checkpoint = SearchCheckpoint.from_wire(checkpoint)
    if checkpoint.kind != kind:
        raise CheckpointRejected(
            f"{kind} engine cannot resume a {checkpoint.kind!r} checkpoint"
        )
    return checkpoint.body


def resume_engine(checkpoint: SearchCheckpoint, extra_reads=()):
    """Rebuild the right engine primed to continue ``checkpoint``; call
    its ``consensus()`` to run the resumed search."""
    if checkpoint.kind == "single":
        from waffle_con_tpu.models.consensus import ConsensusDWFA

        return ConsensusDWFA.resume(checkpoint, extra_reads=extra_reads)
    if checkpoint.kind == "dual":
        from waffle_con_tpu.models.dual_consensus import DualConsensusDWFA

        return DualConsensusDWFA.resume(checkpoint, extra_reads=extra_reads)
    if checkpoint.kind == "priority":
        from waffle_con_tpu.models.priority_consensus import (
            PriorityConsensusDWFA,
        )

        return PriorityConsensusDWFA.resume(
            checkpoint, extra_reads=extra_reads
        )
    raise CheckpointRejected(f"unknown checkpoint kind {checkpoint.kind!r}")


# -- controller ---------------------------------------------------------


class CheckpointController:
    """Per-search snapshot policy, polled by the engines once per pop.

    All mutation happens either on the search thread (inside
    :meth:`poll`) or is a single boolean flag flip from another thread
    (:meth:`request_snapshot`), so no lock is needed.

    ``interval_s``      periodic snapshot cadence (0/None = off).
    ``max_bytes``       drop (do not keep/deliver) snapshots larger than
                        this many serialized bytes (0/None = unbounded).
    ``deadline``        ``time.monotonic()`` deadline: when lapsed, one
                        final snapshot is taken and the standard
                        ``DeadlineExceeded`` is raised at the pop
                        boundary, so an EXPIRED job carries a checkpoint
                        of exactly where it stopped.
    ``snapshot_at_pops``  pinned poll counts for deterministic tests,
                        matched against the controller's cumulative
                        poll counter (equals the pop count for a plain
                        engine; keeps counting across the priority
                        engine's successive group solves); with
                        ``preempt=True`` the pinned snapshot also
                        raises :class:`SearchPreempted`.
    ``on_snapshot``     callback receiving each kept checkpoint.
    """

    def __init__(
        self,
        *,
        interval_s: Optional[float] = None,
        max_bytes: Optional[int] = None,
        deadline: Optional[float] = None,
        snapshot_at_pops=None,
        preempt: bool = False,
        on_snapshot: Optional[Callable[[SearchCheckpoint], None]] = None,
        label: str = "",
    ) -> None:
        self.interval_s = interval_s
        self.max_bytes = max_bytes
        self.deadline = deadline
        self.snapshot_at_pops = (
            frozenset(snapshot_at_pops) if snapshot_at_pops else None
        )
        self.preempt = preempt
        self.on_snapshot = on_snapshot
        self.label = label
        self.last_checkpoint: Optional[SearchCheckpoint] = None
        self.snapshots = 0
        self.bytes_total = 0
        self.oversize_dropped = 0
        self._last_ts = time.monotonic()
        self._polls = 0
        self._requested = False
        self._preempt_requested = False
        self._wrappers: List[Callable[[Dict], Dict]] = []

    # -- cross-thread requests (flag flips only) -----------------------

    def request_snapshot(self, preempt: bool = False) -> None:
        """Ask the search to snapshot at its next pop boundary; with
        ``preempt`` it also stops there with :class:`SearchPreempted`."""
        if preempt:
            self._preempt_requested = True
        self._requested = True

    # -- composite engines (priority wraps its inner dual) -------------

    def push_wrapper(self, fn: Callable[[Dict], Dict]) -> None:
        """Install a body transform applied to every snapshot built
        while it is on the stack (outermost engine last)."""
        self._wrappers.append(fn)

    def pop_wrapper(self) -> None:
        self._wrappers.pop()

    # -- the engine-side seam ------------------------------------------

    def poll(self, pops: int, builder: Callable[[], Dict]) -> None:
        """Called by the engines at the top of every pop iteration with
        the completed-pop count and a zero-argument body builder.
        Builds a snapshot when due; may raise ``DeadlineExceeded`` (with
        the final checkpoint kept) or :class:`SearchPreempted`."""
        cum_polls = self._polls
        self._polls += 1
        preempt = self._preempt_requested
        want = self._requested or preempt
        deadline_hit = (
            self.deadline is not None
            and time.monotonic() >= self.deadline
        )
        want = want or deadline_hit
        if not want and self.snapshot_at_pops is not None:
            if cum_polls in self.snapshot_at_pops:
                want = True
                preempt = preempt or self.preempt
        if not want and self.interval_s:
            want = time.monotonic() - self._last_ts >= self.interval_s
        if not want:
            return
        self._requested = False
        self._preempt_requested = False
        checkpoint = self._build(builder)
        if deadline_hit:
            from waffle_con_tpu.runtime.watchdog import enforce_deadline

            enforce_deadline(self.deadline, label=self.label)
        if preempt and checkpoint is not None:
            raise SearchPreempted(checkpoint)

    def _build(self, builder: Callable[[], Dict]):
        body = builder()
        for wrap in self._wrappers:
            body = wrap(body)
        checkpoint = SearchCheckpoint(body["kind"], body)
        size = checkpoint.byte_size()
        if self.max_bytes and size > self.max_bytes:
            self.oversize_dropped += 1
            logger.warning(
                "checkpoint dropped: %d bytes over the %d cap%s",
                size, self.max_bytes,
                f" ({self.label})" if self.label else "",
            )
            return None
        self._last_ts = time.monotonic()
        self.last_checkpoint = checkpoint
        self.snapshots += 1
        self.bytes_total += size
        if self.on_snapshot is not None:
            try:
                self.on_snapshot(checkpoint)
            except Exception:  # noqa: BLE001 - delivery must never kill
                logger.exception("checkpoint delivery callback failed")
        return checkpoint


#: thread-local controller install (mirrors ops.scorer's thread-local
#: scorer decorator: the serve worker installs per job, engines read)
_TLS = threading.local()


def install_controller(
    controller: Optional[CheckpointController],
) -> Optional[CheckpointController]:
    """Install the calling thread's controller; returns the previous
    one so callers can restore it."""
    previous = getattr(_TLS, "controller", None)
    _TLS.controller = controller
    return previous


def current_controller() -> Optional[CheckpointController]:
    return getattr(_TLS, "controller", None)


@contextmanager
def installed(controller: Optional[CheckpointController]):
    previous = install_controller(controller)
    try:
        yield controller
    finally:
        install_controller(previous)
