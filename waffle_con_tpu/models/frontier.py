"""Frontier-parallel speculation policy (the adaptive-M brain).

The engines' pop loops are serial by contract — byte-parity with the
Python oracle is the bar — but on tie-heavy geometries the queue holds
dozens of near-tied branches that will each be popped and advanced one
column at a time.  :class:`FrontierSpeculator` turns that queue depth
into device occupancy: alongside the in-hand node's ``run_extend`` it
gangs the next-best M−1 queued branches (``SetPriorityQueue.peek_top``)
through the same ``_j_run_ragged`` segment-reduce kernel the serving
arena compiles.  Branches of one search share the scorer — hence band
width — so the kernel's per-row stride is uniform within a self-gang
(the serving arena additionally mixes strides across jobs; see
``WAFFLE_RAGGED_MIXED_W``) and a search self-gangs even outside the
serving stack.

Nothing here affects results: peers' post-run states are held as
consume-once :class:`~waffle_con_tpu.ops.ragged._SpecInjected` deposits
(no slot is touched at gang time) and consumed only after validation
against the real pop's arguments, so every M — including adaptive —
is byte-identical to M=1 by construction.  This module only decides
*how wide* to speculate:

* explicit: ``WAFFLE_FRONTIER_M`` env (wins) or the ``frontier_width``
  config knob — fixed M, clamped to the gang capacity;
* adaptive (default): collapse to 1 on thin frontiers (shallow queue,
  or a positive best-vs-next cost gap — the next pop is not a tie, so
  a peer's predicted arguments would rarely validate), widen with
  queue depth on flat ones, and back off for a cooldown window when
  the rolling gang-commit rate says predictions are not landing.
"""

from __future__ import annotations

from typing import List, Optional

from waffle_con_tpu.ops import ragged as _ragged
from waffle_con_tpu.ops.ragged import GangMember
from waffle_con_tpu.utils import envspec

__all__ = ["FrontierSpeculator", "GangMember", "explicit_width"]


def explicit_width() -> Optional[int]:
    """The ``WAFFLE_FRONTIER_M`` override, or None when unset/garbage.
    0/1 both mean "disabled" (M=1 is the serial search)."""
    env = envspec.get_raw("WAFFLE_FRONTIER_M")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            return None
    return None


class FrontierSpeculator:
    """Per-search frontier-gang launcher + adaptive width policy.

    One instance per engine search (it caches the resolved device
    scorer endpoint and a commit-rate window, both search-local).  The
    engine asks :meth:`width` every pop with whatever frontier state is
    already in hand — queue depth and the best-vs-next cost gap, the
    same signals the ``FrontierSampler`` records — and, when it decides
    to gang, hands :meth:`gang` the in-hand member plus peer
    predictions.  ``run_extend`` then consumes the in-hand deposit
    immediately; peers' deposits wait for their own pops.
    """

    #: hard cap = FrontierGang.G (fixed member-group capacity)
    MAX_M = _ragged.FrontierGang.G
    #: adaptive: don't gang queues shallower than this
    MIN_DEPTH = 4
    #: commit-rate window: resolutions needed before judging, the rate
    #: below which speculation pauses, and the pause length (in pops)
    RATE_WINDOW = 32
    RATE_FLOOR = 0.25
    COOLDOWN_POPS = 512

    def __init__(self, scorer, config=None) -> None:
        self.scorer = scorer
        env = explicit_width()
        cfg_w = getattr(config, "frontier_width", None) if config else None
        self._explicit: Optional[int] = env if env is not None else cfg_w
        self._js = None              # resolved JaxScorer endpoint
        self._probe_failed = False   # scorer has no gangable endpoint
        self._snap = (0, 0)          # (injected, mispredict) window base
        self._cooldown = 0
        self.last_width = 1
        self.last_commit_rate: Optional[float] = None

    # -- endpoint ------------------------------------------------------

    def _endpoint(self, h: int):
        """Resolve (once) the underlying ``JaxScorer`` that owns the
        slots, via the same ``ragged_run_probe`` hop the serve layer
        uses; engines on the python/native backends resolve to None and
        never gang."""
        if self._js is not None:
            return self._js if h in self._js._slot_of else None
        if self._probe_failed:
            return None
        probe = getattr(self.scorer, "ragged_run_probe", None)
        ep = probe(h) if probe is not None else None
        if ep is None:
            self._probe_failed = True
            return None
        self._js = ep[0]
        return self._js

    # -- adaptive width -------------------------------------------------

    def _window_rate(self) -> Optional[float]:
        js = self._js
        if js is None:
            return None
        inj = js.counters.get("run_gang_injected", 0)
        mis = js.counters.get("run_gang_mispredict", 0)
        di = inj - self._snap[0]
        dm = mis - self._snap[1]
        if di + dm <= 0:
            return None
        return di / (di + dm)

    def width(self, queue_depth: int, gap: Optional[int]) -> int:
        """Gang width for this pop (1 = run solo).  ``gap`` is
        ``next_cost - top_cost`` (None when the queue holds one node).
        Pure policy: any return value is byte-safe."""
        if _ragged.serving_active() or not _ragged.enabled():
            w = 1
        elif self._explicit is not None:
            w = max(1, min(int(self._explicit), self.MAX_M))
        elif self._cooldown > 0:
            self._cooldown -= 1
            if self._cooldown == 0:
                # window over: forget the bad stretch and re-try
                self._reset_window()
            w = 1
        elif queue_depth < self.MIN_DEPTH or (gap is not None and gap > 0):
            # thin frontier: the next pops are not ties, peer argument
            # predictions would rarely validate — don't burn a dispatch
            w = 1
        else:
            w = min(self.MAX_M, 1 << max(0, queue_depth.bit_length() - 2))
            rate = self._window_rate()
            self.last_commit_rate = rate
            if rate is not None:
                resolved = (
                    self._js.counters.get("run_gang_injected", 0)
                    - self._snap[0]
                    + self._js.counters.get("run_gang_mispredict", 0)
                    - self._snap[1]
                )
                if resolved >= self.RATE_WINDOW and rate < self.RATE_FLOOR:
                    self._cooldown = self.COOLDOWN_POPS
                    w = 1
        self.last_width = w
        return w

    def _reset_window(self) -> None:
        js = self._js
        if js is not None:
            self._snap = (
                js.counters.get("run_gang_injected", 0),
                js.counters.get("run_gang_mispredict", 0),
            )

    # -- gang launch ----------------------------------------------------

    def gang(self, members: List[GangMember], min_count: int,
             l2: bool) -> int:
        """Dispatch one frontier gang (in-hand member first).  Returns
        the deposit count (0 = nothing ganged; every member simply runs
        solo).  Never raises."""
        if len(members) < 2:
            return 0
        js = self._endpoint(members[0].h)
        if js is None:
            return 0
        from waffle_con_tpu.ops import jax_scorer as _jx

        gang = _ragged.frontier_gang_for(js)
        return gang.run(members, min_count, l2, cols=_jx._run_cols())

    def pending(self, h: int) -> bool:
        """True when a consume-once deposit is waiting for ``h`` —
        engines exclude such nodes from prefetch expansion peeks (their
        next run is already paid for)."""
        js = self._js
        if js is None:
            return False
        gang = getattr(js, "_frontier_gang", None)
        return gang is not None and gang.pending(h)

    def commit_rate(self) -> Optional[float]:
        """Cumulative gang-commit rate for this search's scorer."""
        js = self._js
        if js is None:
            return None
        inj = js.counters.get("run_gang_injected", 0)
        mis = js.counters.get("run_gang_mispredict", 0)
        if inj + mis == 0:
            return None
        return inj / (inj + mis)
