"""Consensus engines (the framework's model families): single, dual,
priority-chain, and multi consensus."""

from waffle_con_tpu.models.consensus import Consensus, ConsensusDWFA, EngineError
from waffle_con_tpu.models.dual_consensus import DualConsensus, DualConsensusDWFA
from waffle_con_tpu.models.multi_consensus import MultiConsensus
from waffle_con_tpu.models.priority_consensus import (
    PriorityConsensus,
    PriorityConsensusDWFA,
)

__all__ = [
    "Consensus",
    "ConsensusDWFA",
    "DualConsensus",
    "DualConsensusDWFA",
    "EngineError",
    "MultiConsensus",
    "PriorityConsensus",
    "PriorityConsensusDWFA",
]
