"""Device-mesh parallelism: sharded wavefront steps with collective vote
reduction."""

from waffle_con_tpu.parallel.mesh import (
    make_mesh,
    sharded_branch_step,
    sharded_consensus_step,
)

__all__ = ["make_mesh", "sharded_branch_step", "sharded_consensus_step"]
