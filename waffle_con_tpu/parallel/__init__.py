"""Device-mesh parallelism: read-sharded scoring with collective vote
reduction."""

from waffle_con_tpu.parallel.mesh import (
    DeviceSet,
    current_device_set,
    device_slices,
    make_mesh,
    probe_device_count,
    reset_probe_cache,
    shard_for_config,
    shard_scorer,
    sharded_col_step,
    use_device_set,
)

__all__ = [
    "DeviceSet", "current_device_set", "device_slices", "make_mesh",
    "probe_device_count", "reset_probe_cache", "shard_for_config",
    "shard_scorer", "sharded_col_step", "use_device_set",
]
