"""Device-mesh parallelism: read-sharded scoring with collective vote
reduction."""

from waffle_con_tpu.parallel.mesh import (
    make_mesh,
    shard_for_config,
    shard_scorer,
    sharded_col_step,
)

__all__ = [
    "make_mesh", "shard_for_config", "shard_scorer", "sharded_col_step",
]
