"""Mesh-parallel wavefront steps.

The consensus framework has two embarrassingly-parallel axes (SURVEY.md
§2, parallelism inventory): *reads* (every read's wavefront advances
independently — the data-parallel axis) and *branches* (live search
hypotheses — a model/batch-parallel axis).  This module maps them onto a
``jax.sharding.Mesh``:

* reads are sharded across chips; each chip advances its read shard's
  wavefronts locally (pure VPU work, no communication);
* the per-step candidate-vote histogram (``[A]`` integer counts), total
  cost, and reached-end flags are reduced with ``lax.psum`` over the read
  axis — small fixed-size collectives that ride ICI;
* branches shard over a second mesh axis with no cross-branch
  communication at all.

This is the TPU-native equivalent of a distributed communication backend
for this workload: the only cross-chip traffic the algorithm needs is the
vote/cost reduction, identical in shape to a gradient ``psum`` in data-
parallel training.  Multi-host DCN scaling uses the same program — a mesh
spanning hosts simply makes the ``psum`` cross DCN.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from waffle_con_tpu.ops.jax_scorer import _stats_row, _update_row


def make_mesh(
    n_devices: Optional[int] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("read",),
) -> Mesh:
    """Build a mesh over the first ``n_devices`` (or all) devices.

    ``shape`` reshapes the device list for multi-axis meshes, e.g.
    ``shape=(2, 4), axis_names=("branch", "read")``.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    arr = np.array(devices)
    if shape is not None:
        arr = arr.reshape(tuple(shape))
    else:
        shape = (len(devices),)
    if len(shape) != len(axis_names):
        raise ValueError("shape and axis_names must have equal rank")
    return Mesh(arr, tuple(axis_names))


def sharded_consensus_step(mesh: Mesh, read_axis: str = "read", num_symbols: int = 32):
    """Build a jitted data-parallel consensus step for one branch.

    Returns ``step(d, e, off, act, cons, clen, reads, rlen, sym, wc, et)
    -> (d', e', votes[num_symbols], ed_total, reached_any, overflow)`` where
    the per-read state and the reads are sharded over ``read_axis`` and the
    reductions are ``psum``-ed over it.  ``votes`` are the integer
    one-tip-symbol read counts; ``ed_total`` is the raw edit-distance sum
    (apply the L1/L2 cost model on the host).  Dense symbol ids must be
    < ``num_symbols``.
    """

    def body(d, e, off, act, cons, clen, reads, rlen, sym, wc, et):
        W = d.shape[1]
        emax = jnp.int32(W // 2)
        kvec = jnp.arange(W, dtype=jnp.int32) - W // 2
        C = cons.shape[0]

        cons2 = cons.at[jnp.clip(clen, 0, C - 1)].set(sym)
        clen2 = clen + 1
        d2, e2, overflow = _update_row(
            d, e, off, act, cons2, clen2, reads, rlen, wc, et, kvec, emax
        )
        eds, occ, _split, reached = _stats_row(
            d2, e2, off, act, cons2, clen2, reads, rlen, num_symbols, kvec
        )
        votes = lax.psum((occ > 0).sum(axis=0), read_axis)
        total = lax.psum(jnp.where(act, eds, 0).sum(), read_axis)
        reached_any = lax.psum(reached.any().astype(jnp.int32), read_axis) > 0
        overflow = lax.psum(overflow.astype(jnp.int32), read_axis) > 0
        return d2, e2, votes, total, reached_any, overflow

    spec_state = P(read_axis, None)
    spec_read = P(read_axis)
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            spec_state,  # d
            spec_read,  # e
            spec_read,  # off
            spec_read,  # act
            P(None),  # cons
            P(),  # clen
            spec_state,  # reads
            spec_read,  # rlen
            P(),  # sym
            P(),  # wc
            P(),  # et
        ),
        out_specs=(
            spec_state,
            spec_read,
            P(None),
            P(),
            P(),
            P(),
        ),
    )
    return jax.jit(sharded)


def sharded_branch_step(mesh: Mesh, branch_axis: str = "branch", read_axis: str = "read", num_symbols: int = 32):
    """Build the 2D-mesh step: branches × reads.

    State carries a leading branch dimension (``d [B, R, W]`` etc.) and a
    per-branch consensus/symbol; branches shard over ``branch_axis``
    (independent, zero communication) while each branch's votes/costs
    reduce over ``read_axis``.  This is the full multi-chip program shape:
    dp over reads, branch-parallel over hypotheses, collectives on ICI.

    Returns ``step(d, e, off, act, cons, clen, reads, rlen, syms, wc, et)
    -> (d', e', votes[B, A], total[B], reached_any[B], overflow)``.
    """

    def one_branch(d, e, off, act, cons, clen, reads, rlen, sym, wc, et):
        W = d.shape[1]
        emax = jnp.int32(W // 2)
        kvec = jnp.arange(W, dtype=jnp.int32) - W // 2
        C = cons.shape[0]

        cons2 = cons.at[jnp.clip(clen, 0, C - 1)].set(sym)
        clen2 = clen + 1
        d2, e2, overflow = _update_row(
            d, e, off, act, cons2, clen2, reads, rlen, wc, et, kvec, emax
        )
        eds, occ, _split, reached = _stats_row(
            d2, e2, off, act, cons2, clen2, reads, rlen, num_symbols, kvec
        )
        return d2, e2, (occ > 0).sum(axis=0), jnp.where(act, eds, 0).sum(), reached.any(), overflow

    def body(d, e, off, act, cons, clen, reads, rlen, syms, wc, et):
        d2, e2, local_votes, local_total, local_reached, local_ovf = jax.vmap(
            one_branch, in_axes=(0, 0, 0, 0, 0, 0, None, None, 0, None, None)
        )(d, e, off, act, cons, clen, reads, rlen, syms, wc, et)
        votes = lax.psum(local_votes, read_axis)
        total = lax.psum(local_total, read_axis)
        reached = lax.psum(local_reached.astype(jnp.int32), read_axis) > 0
        overflow = (
            lax.psum(
                local_ovf.any().astype(jnp.int32), (branch_axis, read_axis)
            )
            > 0
        )
        return d2, e2, votes, total, reached, overflow

    bspec = lambda *rest: P(branch_axis, *rest)  # noqa: E731
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            bspec(read_axis, None),  # d
            bspec(read_axis),  # e
            bspec(read_axis),  # off
            bspec(read_axis),  # act
            bspec(None),  # cons
            bspec(),  # clen
            P(read_axis, None),  # reads
            P(read_axis),  # rlen
            bspec(),  # syms
            P(),  # wc
            P(),  # et
        ),
        out_specs=(
            bspec(read_axis, None),
            bspec(read_axis),
            bspec(None),
            bspec(),
            bspec(),
            P(),
        ),
    )
    return jax.jit(sharded)
