"""Mesh-parallel scoring: reads sharded across chips.

The consensus framework has two embarrassingly-parallel axes (SURVEY.md
§2, parallelism inventory): *reads* (every read's DP column advances
independently — the data-parallel axis) and *branches* (live search
hypotheses).  This module maps the read axis onto a
``jax.sharding.Mesh``:

* :func:`shard_scorer` is the engine-integrated path: it re-places an
  existing :class:`~waffle_con_tpu.ops.jax_scorer.JaxScorer`'s device
  state with a ``NamedSharding`` that splits the read axis across the
  mesh.  Every scorer kernel is a pure jitted function of that state, so
  XLA's SPMD partitioner runs the column DP shard-locally and inserts
  all-reduces exactly where the algorithm needs cross-chip data: the
  per-branch column minima, vote-count sums, and reached/overflow flags.
  The engines (`ConsensusDWFA`, `DualConsensusDWFA`, ...) run unchanged
  on 1 or N devices and produce bit-identical results — the host-side
  fractional-vote arbitration still sees exact integer per-read
  ``occ``/``split`` tables.
* :func:`sharded_col_step` is the same column step expressed explicitly
  with ``shard_map`` + ``psum`` — the hand-written SPMD program, used by
  the parity tests to pin down the communication pattern (votes/costs
  reduce like a data-parallel gradient ``psum``; everything else is
  local VPU work riding ICI-free).

Multi-host DCN scaling uses the same program: a mesh spanning hosts
simply makes the same ``psum`` cross DCN.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from waffle_con_tpu.ops.jax_scorer import _col_step, _stats_core
from waffle_con_tpu.analysis import lockcheck

# jax.shard_map only exists from jax 0.5; older versions (this container
# ships 0.4.x) keep it under the experimental namespace
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map


#: (platform-key -> device count) probe cache.  ``jax.devices()`` is
#: cheap once the backend exists, but the FIRST call initialises the
#: platform — and a missing device plugin makes that initialisation
#: retry (and log) on every call.  shard_for_config used to pay that
#: probe per admitted job; now the answer is taken once per process.
_PROBE_LOCK = lockcheck.make_lock("parallel.mesh.PROBE")
_PROBE_CACHE: Dict[str, int] = {}


def probe_device_count() -> int:
    """Cached local device count for the pinned platform.

    Mirrors ``bench.py``'s device probe contract: an explicit
    ``JAX_PLATFORMS=cpu`` pin is trusted outright — the probe asks the
    already-selected backend and never attempts to initialise another
    plugin — and the outcome (including the count) is cached for the
    process lifetime so per-job placement decisions cost a dict hit.
    """
    key = os.environ.get("JAX_PLATFORMS", "") or "default"
    with _PROBE_LOCK:
        cached = _PROBE_CACHE.get(key)
    if cached is not None:
        return cached
    n = len(jax.devices())
    with _PROBE_LOCK:
        _PROBE_CACHE[key] = n
    return n


def reset_probe_cache() -> None:
    """Forget cached probe outcomes (tests re-pinning platforms)."""
    with _PROBE_LOCK:
        _PROBE_CACHE.clear()


@dataclasses.dataclass(frozen=True)
class DeviceSet:
    """A named, ordered slice of the local device topology.

    Replicas pin their workers to disjoint sets so mesh-sharded jobs
    on different replicas partition onto different chips and run
    concurrently instead of contending for the full device list.
    """

    name: str
    devices: Tuple[Any, ...]

    def __post_init__(self) -> None:
        if not self.devices:
            raise ValueError(f"device set {self.name!r} is empty")

    def __len__(self) -> int:
        return len(self.devices)

    def mesh(self, n_devices: Optional[int] = None,
             shape: Optional[Sequence[int]] = None,
             axis_names: Sequence[str] = ("read",)) -> Mesh:
        return make_mesh(n_devices, shape, axis_names,
                         devices=self.devices)


def device_slices(n_slices: int,
                  devices: Optional[Sequence[Any]] = None,
                  name_prefix: str = "slice") -> List[DeviceSet]:
    """Partition the local devices into ``n_slices`` contiguous sets.

    With at least one device per slice the sets are disjoint (sizes
    differ by at most one); with more slices than devices each slice
    gets one device round-robin — oversubscribed, but every replica
    still owns a valid placement target.
    """
    if n_slices < 1:
        raise ValueError(f"need n_slices >= 1, got {n_slices}")
    devs = tuple(devices) if devices is not None else tuple(jax.devices())
    out: List[DeviceSet] = []
    if len(devs) >= n_slices:
        base, rem = divmod(len(devs), n_slices)
        start = 0
        for i in range(n_slices):
            size = base + (1 if i < rem else 0)
            out.append(DeviceSet(f"{name_prefix}{i}",
                                 devs[start:start + size]))
            start += size
    else:
        for i in range(n_slices):
            out.append(DeviceSet(f"{name_prefix}{i}",
                                 (devs[i % len(devs)],)))
    return out


_TLS = threading.local()


def current_device_set() -> Optional[DeviceSet]:
    """The device set pinned on this thread, or ``None`` (all devices)."""
    return getattr(_TLS, "device_set", None)


@contextlib.contextmanager
def use_device_set(device_set: Optional[DeviceSet]):
    """Pin mesh construction on this thread to ``device_set``.

    Replica worker threads wrap job execution in this scope so the
    existing ``construct_backend -> shard_for_config`` path lands
    sharded state on the replica's slice without plumbing a device
    argument through every layer.
    """
    prev = current_device_set()
    _TLS.device_set = device_set
    try:
        yield device_set
    finally:
        _TLS.device_set = prev


def make_mesh(
    n_devices: Optional[int] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("read",),
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a mesh over the first ``n_devices`` (or all) devices.

    ``shape`` reshapes the device list for multi-axis meshes, e.g.
    ``shape=(2, 4), axis_names=("branch", "read")``.  ``devices``
    overrides the pool the mesh draws from; when omitted, the
    thread's :func:`current_device_set` (if any) wins over the global
    ``jax.devices()`` list so replica threads shard onto their slice.
    """
    if devices is not None:
        devices = list(devices)
    else:
        pinned = current_device_set()
        devices = list(pinned.devices) if pinned is not None \
            else jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} mesh devices but only "
                f"{len(devices)} available"
            )
        devices = devices[:n_devices]
    arr = np.array(devices)
    if shape is not None:
        arr = arr.reshape(tuple(shape))
    else:
        shape = (len(devices),)
    if len(shape) != len(axis_names):
        raise ValueError("shape and axis_names must have equal rank")
    return Mesh(arr, tuple(axis_names))


def shard_scorer(scorer, mesh: Mesh, read_axis: str = "read") -> None:
    """Shard a ``JaxScorer``'s state over the mesh's read axis, in place.

    The scorer's padded read count must be divisible by the mesh size
    (reads are padded to a power of two, so any power-of-two mesh works).
    After this call every kernel the scorer dispatches is partitioned by
    GSPMD: column updates run shard-locally, reductions become ICI
    collectives.  Donated updates preserve the placement, so the state
    stays sharded for the scorer's lifetime.
    """
    if read_axis not in mesh.shape:
        raise ValueError(
            f"mesh has no axis {read_axis!r} (axes: {tuple(mesh.shape)})"
        )
    n = mesh.shape[read_axis]
    if scorer._R % n != 0:
        raise ValueError(
            f"padded read count {scorer._R} not divisible by mesh axis {n}"
        )
    shardings = {
        "D": NamedSharding(mesh, P(None, read_axis, None)),
        "e": NamedSharding(mesh, P(None, read_axis)),
        "rmin": NamedSharding(mesh, P(None, read_axis)),
        "er": NamedSharding(mesh, P(None, read_axis)),
        "off": NamedSharding(mesh, P(None, read_axis)),
        "act": NamedSharding(mesh, P(None, read_axis)),
        "cons": NamedSharding(mesh, P(None, None)),
        "clen": NamedSharding(mesh, P(None)),
    }
    #: the padded-reads copy (dynamic-slice window path) shards like reads;
    #: keyed off-dict so the scorer's state re-placement loop ignores it
    shardings["_reads_pad"] = NamedSharding(mesh, P(read_axis, None))
    scorer._shardings = shardings  # re-applied by the scorer after growth
    scorer._state = {
        name: jax.device_put(arr, shardings[name])
        for name, arr in scorer._state.items()
    }
    scorer._reads = jax.device_put(
        scorer._reads, NamedSharding(mesh, P(read_axis, None))
    )
    scorer._reads_pad = jax.device_put(
        scorer._reads_pad, shardings["_reads_pad"]
    )
    scorer._rlen = jax.device_put(
        scorer._rlen, NamedSharding(mesh, P(read_axis))
    )
    from waffle_con_tpu.runtime import events

    events.record(
        "scorer_sharded", axis=read_axis, shards=n,
        reads=int(scorer._R),
    )


def shard_for_config(scorer, config) -> None:
    """Apply ``config.mesh_shards`` sharding to a fresh ``JaxScorer``.

    One place for the make-a-mesh-and-shard snippet so the supervisor's
    mid-search fallback construction places state exactly like
    ``make_scorer`` does.  The availability check runs against the
    cached :func:`probe_device_count` (or the thread's pinned device
    set), so a config demanding more shards than the platform has
    fails fast without re-initialising a backend per job."""
    shards = getattr(config, "mesh_shards", 0)
    if not shards:
        return
    pinned = current_device_set()
    available = len(pinned) if pinned is not None else probe_device_count()
    if shards > available:
        raise ValueError(
            f"config.mesh_shards={shards} exceeds the "
            f"{available} available device(s)"
            + (f" in device set {pinned.name!r}" if pinned else "")
        )
    shard_scorer(scorer, make_mesh(shards))


def sharded_col_step(mesh: Mesh, read_axis: str = "read", num_symbols: int = 32):
    """Build the explicit shard_map data-parallel column step for one
    branch.

    Returns ``step(D, e, rmin, er, off, act, cons, clen, reads, rlen,
    sym, wc, et) -> (D', e', rmin', er', occ, split, total, reached_any,
    overflow)`` where per-read state and reads are sharded over
    ``read_axis``; ``occ [R, A]``/``split [R]`` stay sharded (exact
    integer tip votes per read — the engines' fractional-vote arithmetic
    needs the full table, not a lossy presence count), while ``total``,
    ``reached_any`` and ``overflow`` are ``psum``-reduced scalars.
    """

    def body(D, e, rmin, er, off, act, cons, clen, reads, rlen, sym, wc, et):
        W = D.shape[1]
        E = jnp.int32((W - 2) // 2)
        C = cons.shape[0]
        cons2 = cons.at[jnp.clip(clen, 0, C - 1)].set(sym)
        clen2 = clen + 1
        D2, e2, rmin2, er2 = _col_step(
            D, e, rmin, er, off, act, rlen, reads, clen2, sym, wc, et, E
        )
        eds, occ, split, reached = _stats_core(
            D2, e2, rmin2, er2, off, act, rlen, reads, clen2, num_symbols, E
        )
        total = lax.psum(jnp.where(act, eds, 0).sum(), read_axis)
        reached_any = lax.psum(reached.any().astype(jnp.int32), read_axis) > 0
        overflow = (
            lax.psum((act & (e2 >= E)).any().astype(jnp.int32), read_axis) > 0
        )
        return D2, e2, rmin2, er2, occ, split, total, reached_any, overflow

    rspec = P(read_axis)
    rwspec = P(read_axis, None)
    sharded = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            rwspec,  # D
            rspec,  # e
            rspec,  # rmin
            rspec,  # er
            rspec,  # off
            rspec,  # act
            P(None),  # cons
            P(),  # clen
            rwspec,  # reads
            rspec,  # rlen
            P(),  # sym
            P(),  # wc
            P(),  # et
        ),
        out_specs=(
            rwspec,
            rspec,
            rspec,
            rspec,
            rwspec,  # occ
            rspec,  # split
            P(),
            P(),
            P(),
        ),
    )
    return jax.jit(sharded)
