"""Span-based host-side tracer with Chrome trace-event export.

The search engines, the scorer instrumentation layer, and the JAX
scorer's device-sync points open nested wall-clock **spans**
(search -> queue-pop batch -> dispatch -> device-sync); finished spans
are recorded as Chrome trace-event ``"ph": "X"`` complete events,
exported with :meth:`Tracer.write_chrome_trace` and viewable in
Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.

Optional ``jax.profiler`` bridge: with the bridge on, every host span
also enters a :class:`jax.profiler.TraceAnnotation`, so when an XLA
device trace is being captured (``jax.profiler.start_trace``) the host
spans line up with the device timeline.  Caveat (README "Observability"):
on CPU-only builds the annotations are inert unless a profiler trace is
active, and annotation names land on the TraceMe timeline, not the XLA
op timeline.

Overhead contract: with tracing off (``WAFFLE_TRACE`` unset and no
programmatic enable), :func:`span` returns a shared no-op context
manager singleton — no allocation, no timestamps, no lock.

Trace contexts (multi-tenant serving): a :class:`TraceContext` gives a
served job its own trace identity — a stable ``trace_id`` string, a
dedicated Chrome ``pid`` (so Perfetto groups each job's spans under its
own process row), and a per-context stack of open span ids that carries
parent linkage *across threads*.  The serve worker activates its job's
context for the duration of the job (:func:`set_current_context`), and
the batching dispatcher re-activates the submitting job's context
around each coalesced dispatch execution, so a span opened on the
dispatcher thread still records the job's ``pid`` and parents under the
worker-side span that submitted it.  The cross-thread hop itself is
stitched with Chrome flow events (:meth:`Tracer.flow`).

Context safety contract: a context's span stack is only ever touched by
the one thread currently *running* the job — the worker parks while the
dispatcher executes its dispatch — so the stack needs no lock.  Context
activation is a plain thread-local assignment and is always on (the
flight recorder reads :func:`current_trace_id` even when tracing is
disabled); spans themselves still cost nothing unless tracing is
enabled.

``WAFFLE_TRACE`` values: ``1`` enables recording; any other non-empty,
non-``0`` value is treated as an output path written at interpreter
exit.  ``WAFFLE_TRACE_JAX=1`` additionally turns on the jax.profiler
bridge.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Dict, List, Optional

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec


class _NullSpan:
    """Shared no-op span: the entire disabled-mode cost."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class TraceContext:
    """Per-job trace identity and cross-thread parent linkage.

    ``trace_id`` names the trace (e.g. ``"consensus/job-3"``),
    ``chrome_pid`` is the Chrome trace ``pid`` the job's spans render
    under, and the span-id stack carries parent linkage for spans opened
    on whichever thread currently runs the job (see module docstring for
    the single-runner safety contract).
    """

    __slots__ = ("trace_id", "chrome_pid", "label", "root_parent",
                 "_stack", "_next_id")

    def __init__(self, trace_id: str, chrome_pid: int, label: str = "",
                 span_base: int = 0,
                 root_parent: Optional[int] = None) -> None:
        self.trace_id = trace_id
        self.chrome_pid = int(chrome_pid)
        self.label = label or trace_id
        #: parent span id for stack-root spans — set on contexts adopted
        #: from another process so the remote tree nests under the
        #: originating side's per-job root span
        self.root_parent = root_parent
        self._stack: List[int] = []
        #: span ids count up from here — adopted contexts get a disjoint
        #: base so ids never collide with the minting process's spans
        self._next_id = int(span_base)

    def _open_span(self) -> "tuple[int, Optional[int]]":
        """Allocate a span id, returning ``(span_id, parent_id)``."""
        parent = self._stack[-1] if self._stack else self.root_parent
        self._next_id += 1
        span_id = self._next_id
        self._stack.append(span_id)
        return span_id, parent

    def _close_span(self, span_id: int) -> None:
        if self._stack and self._stack[-1] == span_id:
            self._stack.pop()
        elif span_id in self._stack:  # unbalanced exit: drop through it
            while self._stack and self._stack.pop() != span_id:
                pass

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id!r}, pid={self.chrome_pid})"


#: Chrome pids for job contexts start here so they can never collide
#: with a real process pid on the same timeline
JOB_PID_BASE = 1_000_000


def context_to_wire(ctx: TraceContext,
                    parent_span_id: Optional[int] = None,
                    span_base: int = 0,
                    flow_id: Optional[int] = None) -> Dict:
    """A :class:`TraceContext` as the plain-JSON dict the proc wire
    ships on SUBMIT (see ``serve.procs.wire.decode_trace`` for the
    receiving-side validation)."""
    return {
        "trace_id": ctx.trace_id,
        "chrome_pid": ctx.chrome_pid,
        "label": ctx.label,
        "parent_span_id": parent_span_id,
        "span_base": int(span_base),
        "flow_id": flow_id,
    }


def context_from_wire(obj: Dict) -> TraceContext:
    """Rebuild an adopted :class:`TraceContext` from a wire dict: same
    trace id and Chrome pid as the minting process, span ids allocated
    from the shipped disjoint base, stack-root spans parented under the
    minting side's per-job root span."""
    return TraceContext(
        str(obj["trace_id"]),
        int(obj["chrome_pid"]),
        label=str(obj.get("label") or ""),
        span_base=int(obj.get("span_base") or 0),
        root_parent=(int(obj["parent_span_id"])
                     if obj.get("parent_span_id") is not None else None),
    )


_CTX = threading.local()


def current_context() -> Optional[TraceContext]:
    """The calling thread's active trace context (``None`` outside a
    served job)."""
    return getattr(_CTX, "ctx", None)


def current_trace_id() -> Optional[str]:
    ctx = getattr(_CTX, "ctx", None)
    return ctx.trace_id if ctx is not None else None


def set_current_context(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install ``ctx`` as the calling thread's trace context; returns
    the previous one so callers can restore it (always-on and cheap: a
    single thread-local assignment)."""
    previous = getattr(_CTX, "ctx", None)
    _CTX.ctx = ctx
    return previous


class _Span:
    """A live span; appends one Chrome complete event on exit.

    The span binds to the calling thread's :class:`TraceContext` at
    entry — a coalesced dispatch executed on the dispatcher thread under
    the job's re-activated context therefore records the job's pid and
    parents under the worker-side span that submitted it.
    """

    __slots__ = (
        "_tracer", "name", "cat", "args", "_start_ns", "_jax_ctx",
        "_ctx", "_span_id", "_parent_id",
    )

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: Dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._jax_ctx = None

    def __enter__(self):
        ann = self._tracer._jax_annotation
        if ann is not None:
            self._jax_ctx = ann(self.name)
            self._jax_ctx.__enter__()
        ctx = current_context()
        self._ctx = ctx
        if ctx is not None:
            self._span_id, self._parent_id = ctx._open_span()
        else:
            self._span_id = self._parent_id = None
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        end_ns = time.perf_counter_ns()
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*(exc or (None, None, None)))
        if self._ctx is not None:
            self._ctx._close_span(self._span_id)
        self._tracer._finish(self, self._start_ns, end_ns)
        return False


class Tracer:
    """Collects finished spans as Chrome trace events.

    Also keeps per-category cumulative inclusive wall time
    (:meth:`category_totals`), which the engines diff across a search to
    build the :class:`~waffle_con_tpu.obs.report.SearchReport` time
    breakdown.
    """

    def __init__(self) -> None:
        self._forced: Optional[bool] = None
        self._lock = lockcheck.make_lock("obs.trace.Tracer")
        self._events: List[Dict] = []
        self._totals: Dict[str, float] = {}
        self._t0_ns = time.perf_counter_ns()
        self._jax_annotation = None  # set by enable_jax_bridge()
        self._pid = os.getpid()
        self._named_pids: set = set()

    # -- enablement ----------------------------------------------------

    @property
    def enabled(self) -> bool:
        if self._forced is not None:
            return self._forced
        return envspec.flag("WAFFLE_TRACE")

    def enable(self, on: bool = True) -> None:
        self._forced = bool(on)

    def reset_enabled(self) -> None:
        self._forced = None

    def enable_jax_bridge(self, on: bool = True) -> bool:
        """Wire spans to ``jax.profiler.TraceAnnotation``; returns
        whether the bridge is active (False if jax is unavailable)."""
        if not on:
            self._jax_annotation = None
            return False
        try:
            from jax.profiler import TraceAnnotation
        except Exception:  # pragma: no cover - jax always present here
            self._jax_annotation = None
            return False
        self._jax_annotation = TraceAnnotation
        return True

    # -- span lifecycle ------------------------------------------------

    def span(self, name: str, cat: str = "host", **args):
        """A context manager timing one nested region; the no-op
        singleton when tracing is disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, cat, args)

    def _finish(self, span: _Span, start_ns: int, end_ns: int) -> None:
        ctx = span._ctx
        event = {
            "name": span.name,
            "cat": span.cat,
            "ph": "X",
            "ts": (start_ns - self._t0_ns) / 1e3,
            "dur": (end_ns - start_ns) / 1e3,
            "pid": self._pid if ctx is None else ctx.chrome_pid,
            "tid": threading.get_ident() % 2**31,
        }
        args = dict(span.args) if span.args else {}
        if ctx is not None:
            args["trace_id"] = ctx.trace_id
            args["span_id"] = span._span_id
            args["parent_id"] = span._parent_id
        if args:
            event["args"] = args
        dt = (end_ns - start_ns) / 1e9
        with self._lock:
            if ctx is not None and ctx.chrome_pid not in self._named_pids:
                self._named_pids.add(ctx.chrome_pid)
                self._events.append({
                    "name": "process_name",
                    "ph": "M",
                    "pid": ctx.chrome_pid,
                    "args": {"name": ctx.label},
                })
            self._events.append(event)
            self._totals[span.cat] = self._totals.get(span.cat, 0.0) + dt

    def flow(self, phase: str, flow_id: int, name: str = "coalesce",
             ctx: Optional[TraceContext] = None) -> None:
        """Append a Chrome flow event (``phase`` ``"s"`` start on the
        submitting thread, ``"f"`` finish on the executing thread) so the
        worker→dispatcher hop renders as an arrow in Perfetto.  ``ctx``
        overrides the thread-local context (the proc door/worker emit
        socket-hop arrows from threads that never activate the job's
        context).  No-op when tracing is disabled."""
        if not self.enabled:
            return
        if ctx is None:
            ctx = current_context()
        event = {
            "name": name,
            "cat": "flow",
            "ph": phase,
            "id": int(flow_id),
            "ts": (time.perf_counter_ns() - self._t0_ns) / 1e3,
            "pid": self._pid if ctx is None else ctx.chrome_pid,
            "tid": threading.get_ident() % 2**31,
        }
        if phase == "f":
            event["bp"] = "e"  # bind finish to enclosing slice
        with self._lock:
            self._events.append(event)

    def record_span(self, ctx: TraceContext, name: str, cat: str,
                    start_mono_s: float, end_mono_s: float,
                    span_id: Optional[int] = None,
                    parent_id: Optional[int] = None, **args) -> None:
        """Append one retrospective complete event under ``ctx``.

        The proc front door uses this for phases it only knows after
        the fact (queue wait, the whole door-side job envelope):
        ``start_mono_s``/``end_mono_s`` are ``time.monotonic()``
        readings, mapped onto this tracer's event clock via a paired
        now-sample of both clocks.  No-op when tracing is disabled.
        """
        if not self.enabled:
            return
        now_us = (time.perf_counter_ns() - self._t0_ns) / 1e3
        now_mono = time.monotonic()
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": now_us - (now_mono - start_mono_s) * 1e6,
            "dur": max(0.0, (end_mono_s - start_mono_s) * 1e6),
            "pid": ctx.chrome_pid,
            "tid": threading.get_ident() % 2**31,
            "args": dict(args, trace_id=ctx.trace_id, span_id=span_id,
                         parent_id=parent_id),
        }
        dt = max(0.0, end_mono_s - start_mono_s)
        with self._lock:
            if ctx.chrome_pid not in self._named_pids:
                self._named_pids.add(ctx.chrome_pid)
                self._events.append({
                    "name": "process_name",
                    "ph": "M",
                    "pid": ctx.chrome_pid,
                    "args": {"name": ctx.label},
                })
            self._events.append(event)
            self._totals[cat] = self._totals.get(cat, 0.0) + dt

    # -- cross-process stitching ---------------------------------------

    def unix_origin_us(self) -> float:
        """Unix-epoch microseconds at ``ts == 0`` on this tracer's event
        clock — shipped alongside drained span buffers so another
        process can rebase them onto its own timeline."""
        return time.time() * 1e6 - (
            time.perf_counter_ns() - self._t0_ns
        ) / 1e3

    def drain_events(self, pid: int,
                     limit: Optional[int] = None) -> List[Dict]:
        """Remove and return this tracer's events for one Chrome pid
        (a served job's synthetic pid) — the worker-side span buffer a
        RESULT/ERROR/CHECKPOINT frame carries back to the door.

        Process-name metadata stays behind (the door names the pid from
        its own side).  ``limit`` keeps only the **latest** events:
        span completion order is children-first, so the tail is where
        the enclosing spans (and the job root) live.
        """
        kept: List[Dict] = []
        out: List[Dict] = []
        with self._lock:
            for event in self._events:
                if event.get("pid") == pid and event.get("ph") != "M":
                    out.append(event)
                else:
                    kept.append(event)
            self._events[:] = kept
        if limit is not None and limit >= 0 and len(out) > limit:
            out = out[len(out) - limit:]
        return out

    def ingest_remote_events(self, events: List[Dict],
                             origin_us: Optional[float] = None,
                             worker: Optional[str] = None) -> int:
        """Merge another process's drained span events into this tracer,
        rebasing their timestamps onto this tracer's clock (each process
        measures ``ts`` from its own epoch; ``origin_us`` is the remote
        :meth:`unix_origin_us`).  Returns the number of events kept."""
        if not self.enabled or not events:
            return 0
        shift = 0.0
        if origin_us is not None:
            try:
                shift = float(origin_us) - self.unix_origin_us()
            except (TypeError, ValueError):
                shift = 0.0
        stitched: List[Dict] = []
        for event in events:
            if not isinstance(event, dict):
                continue
            event = dict(event)
            try:
                event["ts"] = float(event.get("ts", 0.0)) + shift
            except (TypeError, ValueError):
                continue
            if worker:
                args = dict(event.get("args") or {})
                args.setdefault("worker", worker)
                event["args"] = args
            stitched.append(event)
        with self._lock:
            self._events.extend(stitched)
        return len(stitched)

    # -- export --------------------------------------------------------

    def chrome_events(self) -> List[Dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def category_totals(self) -> Dict[str, float]:
        """Cumulative inclusive seconds per span category."""
        with self._lock:
            return dict(self._totals)

    def clear(self) -> None:
        with self._lock:
            del self._events[:]
            self._totals.clear()
            self._named_pids.clear()

    def write_chrome_trace(self, path: str, events: Optional[List[Dict]] = None) -> None:
        """Write a Chrome trace-event JSON file (Perfetto-loadable)."""
        payload = {
            "traceEvents": self.chrome_events() if events is None else events,
            "displayTimeUnit": "ms",
        }
        with open(path, "w") as fh:
            json.dump(payload, fh)


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def span(name: str, cat: str = "host", **args):
    """Module-level shortcut for ``get_tracer().span(...)``."""
    return _TRACER.span(name, cat, **args)


def tracing_enabled() -> bool:
    return _TRACER.enabled


def _env_autosetup() -> None:
    """Honor ``WAFFLE_TRACE=<path>`` (write at exit) and
    ``WAFFLE_TRACE_JAX=1`` once at import."""
    value = envspec.get_raw("WAFFLE_TRACE", "")
    if value not in ("", "0", "1"):
        atexit.register(lambda: _TRACER.write_chrome_trace(value))
    if envspec.flag("WAFFLE_TRACE_JAX"):
        _TRACER.enable_jax_bridge(True)


_env_autosetup()
