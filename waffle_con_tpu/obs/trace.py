"""Span-based host-side tracer with Chrome trace-event export.

The search engines, the scorer instrumentation layer, and the JAX
scorer's device-sync points open nested wall-clock **spans**
(search -> queue-pop batch -> dispatch -> device-sync); finished spans
are recorded as Chrome trace-event ``"ph": "X"`` complete events,
exported with :meth:`Tracer.write_chrome_trace` and viewable in
Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.

Optional ``jax.profiler`` bridge: with the bridge on, every host span
also enters a :class:`jax.profiler.TraceAnnotation`, so when an XLA
device trace is being captured (``jax.profiler.start_trace``) the host
spans line up with the device timeline.  Caveat (README "Observability"):
on CPU-only builds the annotations are inert unless a profiler trace is
active, and annotation names land on the TraceMe timeline, not the XLA
op timeline.

Overhead contract: with tracing off (``WAFFLE_TRACE`` unset and no
programmatic enable), :func:`span` returns a shared no-op context
manager singleton — no allocation, no timestamps, no lock.

``WAFFLE_TRACE`` values: ``1`` enables recording; any other non-empty,
non-``0`` value is treated as an output path written at interpreter
exit.  ``WAFFLE_TRACE_JAX=1`` additionally turns on the jax.profiler
bridge.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Dict, List, Optional


class _NullSpan:
    """Shared no-op span: the entire disabled-mode cost."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """A live span; appends one Chrome complete event on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "_start_ns", "_jax_ctx")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: Dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._jax_ctx = None

    def __enter__(self):
        ann = self._tracer._jax_annotation
        if ann is not None:
            self._jax_ctx = ann(self.name)
            self._jax_ctx.__enter__()
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        end_ns = time.perf_counter_ns()
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*(exc or (None, None, None)))
        self._tracer._finish(self, self._start_ns, end_ns)
        return False


class Tracer:
    """Collects finished spans as Chrome trace events.

    Also keeps per-category cumulative inclusive wall time
    (:meth:`category_totals`), which the engines diff across a search to
    build the :class:`~waffle_con_tpu.obs.report.SearchReport` time
    breakdown.
    """

    def __init__(self) -> None:
        self._forced: Optional[bool] = None
        self._lock = threading.Lock()
        self._events: List[Dict] = []
        self._totals: Dict[str, float] = {}
        self._t0_ns = time.perf_counter_ns()
        self._jax_annotation = None  # set by enable_jax_bridge()
        self._pid = os.getpid()

    # -- enablement ----------------------------------------------------

    @property
    def enabled(self) -> bool:
        if self._forced is not None:
            return self._forced
        return os.environ.get("WAFFLE_TRACE", "") not in ("", "0")

    def enable(self, on: bool = True) -> None:
        self._forced = bool(on)

    def reset_enabled(self) -> None:
        self._forced = None

    def enable_jax_bridge(self, on: bool = True) -> bool:
        """Wire spans to ``jax.profiler.TraceAnnotation``; returns
        whether the bridge is active (False if jax is unavailable)."""
        if not on:
            self._jax_annotation = None
            return False
        try:
            from jax.profiler import TraceAnnotation
        except Exception:  # pragma: no cover - jax always present here
            self._jax_annotation = None
            return False
        self._jax_annotation = TraceAnnotation
        return True

    # -- span lifecycle ------------------------------------------------

    def span(self, name: str, cat: str = "host", **args):
        """A context manager timing one nested region; the no-op
        singleton when tracing is disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, cat, args)

    def _finish(self, span: _Span, start_ns: int, end_ns: int) -> None:
        event = {
            "name": span.name,
            "cat": span.cat,
            "ph": "X",
            "ts": (start_ns - self._t0_ns) / 1e3,
            "dur": (end_ns - start_ns) / 1e3,
            "pid": self._pid,
            "tid": threading.get_ident() % 2**31,
        }
        if span.args:
            event["args"] = span.args
        dt = (end_ns - start_ns) / 1e9
        with self._lock:
            self._events.append(event)
            self._totals[span.cat] = self._totals.get(span.cat, 0.0) + dt

    # -- export --------------------------------------------------------

    def chrome_events(self) -> List[Dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def category_totals(self) -> Dict[str, float]:
        """Cumulative inclusive seconds per span category."""
        with self._lock:
            return dict(self._totals)

    def clear(self) -> None:
        with self._lock:
            del self._events[:]
            self._totals.clear()

    def write_chrome_trace(self, path: str, events: Optional[List[Dict]] = None) -> None:
        """Write a Chrome trace-event JSON file (Perfetto-loadable)."""
        payload = {
            "traceEvents": self.chrome_events() if events is None else events,
            "displayTimeUnit": "ms",
        }
        with open(path, "w") as fh:
            json.dump(payload, fh)


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def span(name: str, cat: str = "host", **args):
    """Module-level shortcut for ``get_tracer().span(...)``."""
    return _TRACER.span(name, cat, **args)


def tracing_enabled() -> bool:
    return _TRACER.enabled


def _env_autosetup() -> None:
    """Honor ``WAFFLE_TRACE=<path>`` (write at exit) and
    ``WAFFLE_TRACE_JAX=1`` once at import."""
    value = os.environ.get("WAFFLE_TRACE", "")
    if value not in ("", "0", "1"):
        atexit.register(lambda: _TRACER.write_chrome_trace(value))
    if os.environ.get("WAFFLE_TRACE_JAX", "") not in ("", "0"):
        _TRACER.enable_jax_bridge(True)


_env_autosetup()
