"""Rolling SLO windows: sliding percentiles + EWMA over recent latency.

The fixed-bucket histograms in :mod:`waffle_con_tpu.obs.metrics`
accumulate forever, so they cannot answer "is this search slow
*relative to the last five minutes*".  This module keeps **sliding
windows** (age- and count-bounded) over the two latencies that define
the serving SLO — per-dispatch wall clock and per-job/search wall
clock — and derives nearest-rank p50/p95/p99 plus an EWMA baseline
from each.

Anomaly hook: :func:`observe_search` first *checks* the elapsed time
against the job window's rolling p95 (before adding the sample, so a
pathological search cannot dilute the baseline it is judged against)
and fires the flight recorder's ``slow_search`` trigger when
``elapsed > k * p95``; only then does the sample join the window.  The
check needs :data:`MIN_SAMPLES` prior samples — cold windows never
alarm.

Exposition: the tracker registers a **collector** with the process
metrics registry on first use, so every
:meth:`~waffle_con_tpu.obs.metrics.MetricsRegistry.snapshot` /
``render_prometheus`` call re-publishes
``waffle_slo_dispatch_latency_seconds`` /
``waffle_slo_job_latency_seconds`` gauges (labelled
``quantile="p50"|"p95"|"p99"|"ewma"``) plus per-window sample counts.
:func:`snapshot` returns the same data as a JSON-ready dict for
``bench.py --serve`` evidence, incident dumps, and ``waffle_top``.

Knobs: ``WAFFLE_SLO_WINDOW_S`` (window age, default 300s),
``WAFFLE_SLO_K`` (slow-search multiplier, default 3.0).
"""

from __future__ import annotations

import collections
import time
from typing import Deque, Dict, Optional, Tuple

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec

DEFAULT_WINDOW_S = 300.0
DEFAULT_K = 3.0
#: slow-search checks need this many prior samples in the job window
MIN_SAMPLES = 20
#: EWMA smoothing factor (weight of the newest sample)
EWMA_ALPHA = 0.1

QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


def window_age_s() -> float:
    try:
        return float(envspec.get_raw("WAFFLE_SLO_WINDOW_S", "") or
                     DEFAULT_WINDOW_S)
    except ValueError:
        return DEFAULT_WINDOW_S


def slow_search_k() -> float:
    try:
        return float(envspec.get_raw("WAFFLE_SLO_K", "") or DEFAULT_K)
    except ValueError:
        return DEFAULT_K


class RollingWindow:
    """Age- and count-bounded sample window with EWMA baseline.

    Not thread-safe on its own; :class:`SloTracker` serializes access.
    """

    __slots__ = ("max_age_s", "_samples", "ewma", "total")

    def __init__(self, max_age_s: float, max_count: int) -> None:
        self.max_age_s = max_age_s
        self._samples: Deque[Tuple[float, float]] = collections.deque(
            maxlen=max_count
        )
        self.ewma: Optional[float] = None
        self.total = 0

    def observe(self, value: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._samples.append((now, float(value)))
        self.total += 1
        if self.ewma is None:
            self.ewma = float(value)
        else:
            self.ewma += EWMA_ALPHA * (float(value) - self.ewma)
        self._prune(now)

    def _prune(self, now: float) -> None:
        cutoff = now - self.max_age_s
        samples = self._samples
        while samples and samples[0][0] < cutoff:
            samples.popleft()

    def percentiles(self, now: Optional[float] = None) -> Dict[str, float]:
        """Nearest-rank p50/p95/p99 over the live window (empty dict
        when the window has no samples)."""
        self._prune(time.monotonic() if now is None else now)
        values = sorted(v for _ts, v in self._samples)
        if not values:
            return {}
        n = len(values)
        return {
            name: values[min(n - 1, max(0, int(q * n + 0.5) - 1))]
            for name, q in QUANTILES
        }

    def __len__(self) -> int:
        return len(self._samples)


class SloTracker:
    """Dispatch-latency + job-latency windows with slow-search check."""

    WINDOW_NAMES = ("dispatch", "job")

    def __init__(self, window_s: Optional[float] = None) -> None:
        age = window_age_s() if window_s is None else window_s
        self._lock = lockcheck.make_lock("obs.slo.SloTracker")
        self._windows: Dict[str, RollingWindow] = {
            "dispatch": RollingWindow(age, max_count=4096),
            "job": RollingWindow(age, max_count=1024),
        }
        self.slow_searches = 0

    def observe_dispatch(self, seconds: float) -> None:
        with self._lock:
            self._windows["dispatch"].observe(seconds)

    def observe_job(self, seconds: float) -> None:
        with self._lock:
            self._windows["job"].observe(seconds)

    def observe_search(self, seconds: float,
                       trace_id: Optional[str] = None) -> bool:
        """Check ``seconds`` against the rolling job p95 *before* adding
        it to the window; fire the ``slow_search`` flight trigger (and
        return True) when ``seconds > k * p95`` with a warm window."""
        k = slow_search_k()
        slow = False
        with self._lock:
            window = self._windows["job"]
            if len(window) >= MIN_SAMPLES:
                p95 = window.percentiles().get("p95")
                if p95 is not None and seconds > k * p95:
                    slow = True
                    self.slow_searches += 1
                    baseline = p95
            window.observe(seconds)
        if slow:
            from waffle_con_tpu.obs import flight
            from waffle_con_tpu.obs import metrics as obs_metrics

            flight.trigger(
                "slow_search", trace_id=trace_id,
                elapsed_s=round(seconds, 6), p95_s=round(baseline, 6),
                k=k,
            )
            if obs_metrics.metrics_enabled():
                obs_metrics.registry().counter(
                    "waffle_slo_slow_search_total"
                ).inc()
        return slow

    def snapshot(self) -> Dict:
        """JSON-ready rolling stats per window (embedded in bench
        evidence, incident dumps, and the waffle_top poll)."""
        out: Dict = {"k": slow_search_k(), "slow_searches": 0}
        with self._lock:
            out["slow_searches"] = self.slow_searches
            for name, window in self._windows.items():
                stats = window.percentiles()
                out[name] = {
                    "window_s": window.max_age_s,
                    "count": len(window),
                    "total": window.total,
                    "ewma_s": window.ewma,
                    **{f"{q}_s": v for q, v in stats.items()},
                }
        return out

    def publish(self, registry) -> None:
        """Set ``waffle_slo_*`` gauges on ``registry`` from the live
        windows (collector hook; skips empty windows so unit-test
        registries stay untouched by cold trackers)."""
        with self._lock:
            if not any(len(w) for w in self._windows.values()):
                return
            for name, window in self._windows.items():
                if not len(window):
                    continue
                family = f"waffle_slo_{name}_latency_seconds"
                for q, v in window.percentiles().items():
                    registry.gauge(family, quantile=q).set(v)
                if window.ewma is not None:
                    registry.gauge(family, quantile="ewma").set(window.ewma)
                registry.gauge(
                    "waffle_slo_window_samples", window=name
                ).set(len(window))
            registry.gauge("waffle_slo_slow_searches").set(
                self.slow_searches
            )

    def reset(self) -> None:
        with self._lock:
            age = window_age_s()
            self._windows = {
                "dispatch": RollingWindow(age, max_count=4096),
                "job": RollingWindow(age, max_count=1024),
            }
            self.slow_searches = 0


_TRACKER = SloTracker()
_COLLECTOR_REGISTERED = False
_COLLECTOR_LOCK = lockcheck.make_lock("obs.slo.COLLECTOR")


def tracker() -> SloTracker:
    return _TRACKER


def _ensure_collector() -> None:
    """Register the exposition collector with the process registry once
    (lazily, on first observation, to keep import side-effect free)."""
    global _COLLECTOR_REGISTERED
    if _COLLECTOR_REGISTERED:
        return
    with _COLLECTOR_LOCK:
        if _COLLECTOR_REGISTERED:
            return
        from waffle_con_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.registry()
        reg.register_collector(lambda: _TRACKER.publish(reg))
        _COLLECTOR_REGISTERED = True


def observe_dispatch(seconds: float) -> None:
    _ensure_collector()
    _TRACKER.observe_dispatch(seconds)


def observe_job(seconds: float) -> None:
    _ensure_collector()
    _TRACKER.observe_job(seconds)


def observe_search(seconds: float, trace_id: Optional[str] = None) -> bool:
    _ensure_collector()
    return _TRACKER.observe_search(seconds, trace_id=trace_id)


def snapshot() -> Dict:
    return _TRACKER.snapshot()


def reset() -> None:
    _TRACKER.reset()
