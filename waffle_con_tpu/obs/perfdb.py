"""Persistent performance history: an append-only JSONL perf database.

The bench trajectory used to survive only as hand-named
``BENCH_r0*.json`` snapshots plus a frozen steps/s constant in
``scripts/ci.sh``; nothing machine-readable connected one round's
number to the next.  This module is the durable record: every
``bench.py`` / ``scripts/ci.sh`` / ``scripts/profile_scorer.py`` run
appends one schema-versioned record, ``scripts/perf_report.py``
renders the trend, and the CI steps/s gate compares the latest run
against a **rolling baseline** (median of the recent history) with a
tolerance band instead of a hardcoded floor (the absolute floor is
kept as a backstop).

Records are one JSON object per line::

    {"schema": 1, "kind": "microbench", "unix_time": ..., "host": ...,
     "platform": "cpu", "metric": ..., "value": 1063.2,
     "unit": "steps/s", "run_cols": 4, "phases": {...}, ...}

``schema`` is the perfdb record major; readers skip records with a
LARGER major than they understand (forward-written history must not
brick an older reader) and tolerate unparsable lines (a torn write
from a killed bench must not poison the database).

The database path is ``WAFFLE_PERFDB`` when set, else
``evidence/perfdb.jsonl`` under the repository root — inside the repo
so the history is a retained artifact, not a tmpfile.

This module also owns the **bench evidence schema** contract: every
JSON line ``bench.py`` prints carries ``"schema":
EVIDENCE_SCHEMA`` and :func:`load_evidence` validates/rejects unknown
majors; ``tests/test_evidence_schema.py`` pins the field contract the
``scripts/ci.sh`` asserts grep for.
"""

from __future__ import annotations

import json
import os
import platform as _platform
import time
from typing import Dict, List, Optional, Tuple

from waffle_con_tpu.utils import envspec

#: perfdb record major: bump ONLY on a field-meaning change readers
#: cannot tolerate; additive fields do not bump it
SCHEMA = 1

#: bench evidence-line major (the ``"schema"`` field on every JSON
#: line bench.py prints).  2 = the performance-observatory format:
#: versioned lines, optional ``phases`` breakdown, perfdb appends.
#: (1 is the retroactive name for the unversioned pre-observatory
#: lines; a missing ``schema`` field parses as 1.)
EVIDENCE_SCHEMA = 2

DEFAULT_RELPATH = os.path.join("evidence", "perfdb.jsonl")

#: record kind for per-job placement outcomes (serve/placement.py
#: learns mesh-vs-arena routing from these; written only under
#: ``WAFFLE_PLACEMENT_LEARNED`` so the checked-in history stays clean)
PLACEMENT_KIND = "placement_profile"

#: evidence fields every mode must carry (ci.sh bench smoke asserts
#: "metric"; the rest are the cross-mode invariants)
EVIDENCE_REQUIRED = ("metric", "value", "unit", "schema")

#: per-mode required fields — the exact contract scripts/ci.sh's
#: assert blocks read (tests/test_evidence_schema.py cross-checks this
#: table against the ci.sh source, so drift fails tier-1)
EVIDENCE_MODE_FIELDS: Dict[str, Tuple[str, ...]] = {
    "serve": (
        "jobs", "jobs_per_s", "parity", "p50_job_latency_s",
        "p95_job_latency_s", "serve_stats", "mean_batch_occupancy",
        "slo", "incidents",
    ),
    "serve-mix": (
        "parity", "ragged_occupancy", "compiles_ragged",
        "ragged_stats", "bucketed_run_occupancy", "jobs_per_s_ragged",
        "mixed_w",
    ),
    "storm": (
        "parity", "jobs_per_s", "jobs_per_s_single",
        "speedup_vs_single", "p95_job_latency_s", "p99_job_latency_s",
        "replicas", "per_replica", "mesh_placed", "shed",
    ),
    "storm-procs": (
        "parity", "procs", "jobs_per_s", "jobs_per_s_single",
        "speedup_vs_single", "p95_job_latency_s", "p99_job_latency_s",
        "per_worker", "workers_participating", "requeues",
        "worker_lost_incidents", "mesh_placed", "fleet",
    ),
    # the crash drill (--kill-worker) is its own mode: migration
    # accounting fields on top of the storm-procs shape, and a mode
    # string the trend baseline never selects
    "storm-procs-ckpt": (
        "parity", "procs", "jobs_per_s", "per_worker",
        "worker_lost_incidents", "checkpoints", "migrated",
        "restarted_started", "wasted_work_s", "migration_jobs",
        "fleet",
    ),
    "storm-cache": (
        "parity", "jobs_per_s", "hit_rate", "cache_hits", "cache",
        "exact_hits_dispatch_free", "checkpoint_hits_all_iters",
        "checkpoint_jobs", "resumed_wall_total_s",
        "scratch_wall_total_s", "statuses", "slo", "incidents",
    ),
    "microbench": ("parity", "steps", "stop_code", "breakdown"),
    "north-star": ("parity", "vs_baseline", "breakdown"),
}


def default_path() -> str:
    env = envspec.get_raw("WAFFLE_PERFDB", "")
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(root, DEFAULT_RELPATH)


def make_record(kind: str, metric: str, value: float, unit: str,
                **extra) -> Dict:
    """A schema-stamped perfdb record; ``extra`` fields ride along
    verbatim (``phases``, ``run_cols``, ``occupancy``, ...)."""
    rec = {
        "schema": SCHEMA,
        "kind": kind,
        "unix_time": round(time.time(), 3),
        "host": _platform.node() or "unknown",
        "machine": _platform.machine() or "unknown",
        "metric": metric,
        "value": value,
        "unit": unit,
    }
    rec.update(extra)
    return rec


def append_record(record: Dict, path: Optional[str] = None) -> str:
    """Append one record (newline-delimited JSON) to the database,
    creating the parent directory on first write; returns the path."""
    if int(record.get("schema", 0)) != SCHEMA:
        raise ValueError(
            f"refusing to write schema {record.get('schema')!r} "
            f"record (writer is schema {SCHEMA})"
        )
    path = path or default_path()
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(record, sort_keys=True) + "\n")
    return path


def load_records(path: Optional[str] = None,
                 kind: Optional[str] = None) -> List[Dict]:
    """Parse the database, oldest first.  Unparsable lines are skipped
    (torn writes); records with a NEWER major than :data:`SCHEMA` are
    skipped too (never guess at a future format).  ``kind`` filters to
    one record kind."""
    path = path or default_path()
    out: List[Dict] = []
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return out
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        try:
            major = int(rec.get("schema", 0))
        except (TypeError, ValueError):
            continue
        if major > SCHEMA or major < 1:
            continue
        if kind is not None and rec.get("kind") != kind:
            continue
        out.append(rec)
    return out


def rolling_baseline(records: List[Dict], metric: Optional[str] = None,
                     window: int = 10) -> Optional[float]:
    """Median ``value`` of the last ``window`` records (optionally
    filtered to one metric name) — the CI gate's baseline.  ``None``
    when there is no usable history."""
    values = [
        float(r["value"]) for r in records
        if isinstance(r.get("value"), (int, float))
        and (metric is None or r.get("metric") == metric)
    ][-window:]
    if not values:
        return None
    values.sort()
    n = len(values)
    mid = n // 2
    return values[mid] if n % 2 else (values[mid - 1] + values[mid]) / 2


# -- placement profiles (serve/placement.py learned routing) ----------


def reads_bucket(n_reads: int) -> int:
    """Pow2 geometry bucket a placement profile is keyed by — the same
    rounding the scorers apply to their read axis, so jobs that compile
    to the same geometry share one rolling history."""
    n = max(int(n_reads), 1)
    return 1 << (n - 1).bit_length()


def decision_seconds(record: Dict) -> Optional[float]:
    """The seconds a placement decision compares for one profile
    record: the attributable dispatch time (``host_prep +
    device_compute + transfer`` from the record's ``phases`` dict) when
    phase profiling captured it, else the job wall seconds in
    ``value``.  ``None`` for a record carrying neither."""
    phases = record.get("phases")
    if isinstance(phases, dict):
        parts = [phases.get(k)
                 for k in ("host_prep", "device_compute", "transfer")]
        if all(isinstance(p, (int, float)) for p in parts):
            return float(sum(parts))
    value = record.get("value")
    return float(value) if isinstance(value, (int, float)) else None


def substrate_medians(records: List[Dict], bucket: int,
                      window: int = 32) -> Dict[str, Dict]:
    """Rolling per-substrate decision-seconds medians for one reads
    bucket: ``{"mesh": {"n": ..., "median": ...}, "arena": {...}}``
    with absent substrates omitted.  ``records`` is a
    :data:`PLACEMENT_KIND` record list (oldest first, as
    :func:`load_records` returns); ``window`` bounds how much history
    per substrate counts."""
    out: Dict[str, Dict] = {}
    for substrate in ("mesh", "arena"):
        values = [
            s for s in (
                decision_seconds(r) for r in records
                if r.get("kind") == PLACEMENT_KIND
                and r.get("substrate") == substrate
                and r.get("reads_bucket") == bucket
            ) if s is not None
        ][-window:]
        if values:
            values.sort()
            n = len(values)
            mid = n // 2
            median = (values[mid] if n % 2
                      else (values[mid - 1] + values[mid]) / 2)
            out[substrate] = {"n": n, "median": median}
    return out


# -- bench evidence schema --------------------------------------------


def stamp_evidence(out: Dict) -> Dict:
    """Stamp a bench evidence line with the current schema major
    (bench.py calls this on every line it prints)."""
    out["schema"] = EVIDENCE_SCHEMA
    return out


def load_evidence(line_or_dict) -> Dict:
    """Parse and validate one bench evidence line.

    Raises ``ValueError`` for: unparsable JSON, an unknown (newer)
    schema major, or a line missing the cross-mode required fields.
    A missing ``schema`` field parses as major 1 (the pre-observatory
    format) and skips the field checks newer majors guarantee."""
    if isinstance(line_or_dict, str):
        evidence = json.loads(line_or_dict)
    else:
        evidence = dict(line_or_dict)
    if not isinstance(evidence, dict):
        raise ValueError("evidence line is not a JSON object")
    major = int(evidence.get("schema", 1))
    if major > EVIDENCE_SCHEMA:
        raise ValueError(
            f"evidence schema {major} is newer than this reader "
            f"(max {EVIDENCE_SCHEMA}); refusing to guess"
        )
    if major < 1:
        raise ValueError(f"nonsense evidence schema {major}")
    if major >= 2:
        missing = [k for k in EVIDENCE_REQUIRED if k not in evidence]
        if missing:
            raise ValueError(f"evidence line missing {missing}")
        mode = evidence.get("mode")
        for key in EVIDENCE_MODE_FIELDS.get(mode, ()):
            if key not in evidence:
                raise ValueError(
                    f"mode {mode!r} evidence missing {key!r}"
                )
    return evidence
