"""Search audit plane: deterministic decision recorder + lockstep shadow.

Byte-parity against the python oracle is the repo's crown invariant, but
end-of-run equality gives zero triage signal when it breaks.  This module
records what the search *decided* — one compact record per pop boundary —
and compares two runs decision-by-decision:

* **Recorder** (``WAFFLE_AUDIT=1``): each engine pop loop fetches one
  :class:`AuditSink` per search (:func:`search_sink`; ``None`` when
  disabled — the per-pop cost of a disabled run is a single ``is not
  None`` check, decided at search start like ``lockcheck``) and emits a
  record carrying the node identity ``(consensus_len, prefix crc32,
  active-mask digest, priority, seq)``, the dispatch kind (plain branch /
  K-block run / mega / gang), the stop code, and the committed symbols.
  Everything digested is a host scalar the engine already fetched —
  WL002: no new device syncs.  Records stream to
  ``WAFFLE_AUDIT_DIR/audit-<n>-<engine>.jsonl`` when the dir is set and
  always land in a bounded in-memory ring (``WAFFLE_AUDIT_RING``).

* **Decision map** (:func:`expand_units`): pop *order* differs benignly
  across compositions (mega-on-vs-off, K=4-vs-K=1, resumed-vs-scratch
  reorder the frontier), so records are compared as an order-independent
  map from node identity to decision.  A run/mega/gang record with S
  committed symbols expands into S single-step units (prefix crc chained
  incrementally), which line up exactly with the oracle's plain
  single-step pops.  One-sided keys are benign frontier differences;
  the *same key with a different decision* is a divergence.
  ``ignored``/``arena``/``final``/``dispatch`` records are diagnostics
  and expand to no compared units (capacity/ignore choices are
  order-dependent by design).

* **First-divergence differ** (:func:`diff_logs`): aligns two record
  streams (jax-vs-python, mega-on-vs-off, resumed-vs-scratch, ...) and
  reports the first conflicting unit in the left log's emission order —
  exact pop index, both records, and the prefix identity at that point.

* **Lockstep shadow** (``WAFFLE_SHADOW=python``): :func:`maybe_shadow`
  runs the python-oracle twin of a single/dual search in-process, in a
  second thread, feeding both record streams through a
  :class:`_LockstepComparator`; the first conflicting decision raises
  :class:`ParityDivergence` and fires exactly one ``parity_divergence``
  flight incident carrying the diff.  Shadow mode is a **debug tool** —
  it doubles the search and must never be enabled in serve paths.
  Under shadow the primary skips the opaque arena fast path
  (``AuditSink.strict_align``) so every decision stays per-pop
  comparable; the oracle has no fast paths to skip.

``scripts/waffle_diverge.py`` builds the triage loop on top: offline
diff, an auto-minimizer that replays the recorded prefix through the
checkpoint ``resume`` seam, and the seeded-divergence CI drill.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import zlib
from contextlib import contextmanager
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.utils import envspec

#: engines the lockstep shadow knows how to twin (priority searches are
#: shadowed per inner dual-engine group solve, which flows through the
#: ``"dual"`` label here)
SHADOW_ENGINES = ("single", "dual")

#: default bounded ring size per search when ``WAFFLE_AUDIT_RING`` unset
RING_DEFAULT = 4096

#: how many tail prefix bytes each record carries for human triage (the
#: full prefix is recoverable from a checkpoint/repro, not the record)
_TAIL_BYTES = 12

_TLS = threading.local()

_STATS_LOCK = lockcheck.make_lock("obs.audit.stats")
_STATS = {"records": 0, "shadow_pops": 0, "divergences": 0}

#: most recent sinks (any mode), newest last — the parity dump-on-fail
#: hook bundles the last two
_RECENT_LOCK = lockcheck.make_lock("obs.audit.recent")
_RECENT: List["AuditSink"] = []
_RECENT_CAP = 4
_SINK_SEQ = [0]


class ParityDivergence(RuntimeError):
    """The lockstep shadow found a decision the primary and the oracle
    disagree on.  ``detail`` carries the first-divergence diff."""

    def __init__(self, detail: Dict) -> None:
        key = detail.get("key")
        super().__init__(
            f"parity divergence at pop {detail.get('pop_a')} "
            f"(shadow pop {detail.get('pop_b')}): key={key} "
            f"primary={detail.get('value_a')} oracle={detail.get('value_b')}"
        )
        self.detail = detail


# -- digests -----------------------------------------------------------


def crc_bytes(data: bytes, prev: int = 0) -> int:
    """Running CRC32 (the incremental digest units chain with)."""
    return zlib.crc32(data, prev) & 0xFFFFFFFF


def active_digest(*active_sets: Iterable) -> int:
    """Order-insensitive digest of one or more active-read collections
    (host-side index lists/sets the engines already maintain)."""
    d = 0
    for act in active_sets:
        text = ",".join(str(int(a)) for a in sorted(act))
        d = crc_bytes(text.encode() + b"|", d)
    return d


def b64(data: bytes) -> str:
    return base64.b64encode(bytes(data)).decode("ascii")


def unb64(text: str) -> bytes:
    return base64.b64decode(text.encode("ascii"))


def tail(consensus: bytes) -> str:
    return b64(bytes(consensus[-_TAIL_BYTES:]))


# -- enablement & sink plumbing ---------------------------------------


def audit_enabled() -> bool:
    if getattr(_TLS, "provider", None) is not None:
        return True
    return envspec.flag("WAFFLE_AUDIT")


def _ring_cap() -> int:
    cap = envspec.get_int("WAFFLE_AUDIT_RING", RING_DEFAULT)
    return cap if cap > 0 else RING_DEFAULT


class AuditSink:
    """Per-search decision record sink: bounded ring + optional JSONL
    stream + optional ``on_emit`` tap (the lockstep comparator)."""

    def __init__(
        self,
        engine: str,
        ring: Optional[int] = None,
        path: Optional[str] = None,
        on_emit: Optional[Callable[[Dict], None]] = None,
        strict_align: bool = False,
    ) -> None:
        self.engine = engine
        self.path = path
        self.on_emit = on_emit
        #: engines skip opaque subtree fast paths (arena) when set, so
        #: every decision stays per-pop comparable under lockstep shadow
        self.strict_align = strict_align
        self._ring_cap = ring
        self._seq = 0
        self.records: List[Dict] = []

    def emit(self, record: Dict) -> None:
        record["eng"] = self.engine
        record["seq"] = self._seq
        self._seq += 1
        self.records.append(record)
        cap = self._ring_cap
        if cap is not None and len(self.records) > cap:
            del self.records[: len(self.records) - cap]
        if self.path is not None:
            try:
                with open(self.path, "a") as fh:
                    fh.write(json.dumps(record) + "\n")
            except OSError:  # a broken audit sink must never fail a search
                self.path = None
        with _STATS_LOCK:
            _STATS["records"] += 1
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().counter(
                "waffle_audit_records_total", engine=self.engine
            ).inc()
        if self.on_emit is not None:
            self.on_emit(record)


def _default_sink(engine: str) -> AuditSink:
    path = None
    audit_dir = envspec.get_raw("WAFFLE_AUDIT_DIR", "")
    with _RECENT_LOCK:
        _SINK_SEQ[0] += 1
        n = _SINK_SEQ[0]
    if audit_dir:
        try:
            os.makedirs(audit_dir, exist_ok=True)
            path = os.path.join(audit_dir, f"audit-{n:04d}-{engine}.jsonl")
        except OSError:
            path = None
    return AuditSink(engine, ring=_ring_cap(), path=path)


def search_sink(engine: str) -> Optional[AuditSink]:
    """One sink per search, fetched once by each engine's
    ``_consensus_impl``; ``None`` when auditing is off (the zero-overhead
    decision, made at search start)."""
    provider = getattr(_TLS, "provider", None)
    if provider is not None:
        sink = provider(engine)
    elif envspec.flag("WAFFLE_AUDIT"):
        sink = _default_sink(engine)
    else:
        return None
    if sink is not None:
        _TLS.current_sink = sink  # the dispatch-seam tap emits here
        with _RECENT_LOCK:
            _RECENT.append(sink)
            if len(_RECENT) > _RECENT_CAP:
                del _RECENT[: len(_RECENT) - _RECENT_CAP]
    return sink


@contextmanager
def capture(strict_align: bool = False):
    """Install a thread-local sink provider capturing every search's
    records in memory; yields the (growing) list of sinks.  Wins over the
    env default — the drill and tests use it to record without touching
    the environment."""
    sinks: List[AuditSink] = []

    def provider(engine: str) -> AuditSink:
        sink = AuditSink(engine, ring=None, strict_align=strict_align)
        sinks.append(sink)
        return sink

    prev = getattr(_TLS, "provider", None)
    _TLS.provider = provider
    try:
        yield sinks
    finally:
        _TLS.provider = prev


def stats_snapshot() -> Dict[str, int]:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def status() -> Optional[Dict]:
    """Compact audit/shadow status for the ``WAFFLE_STATS_FILE`` payload
    and bench evidence; ``None`` when the plane is fully inactive (so
    disabled payloads carry no ``audit`` key at all)."""
    snap = stats_snapshot()
    enabled = envspec.flag("WAFFLE_AUDIT")
    shadow = _shadow_mode()
    if not enabled and not shadow and not any(snap.values()):
        return None
    snap["enabled"] = enabled
    snap["shadow"] = shadow or None
    return snap


# -- unit expansion & the first-divergence differ ----------------------


def _specs_value(specs: List) -> Tuple:
    canon = tuple(
        (str(k), None if a is None else int(a), None if c is None else int(c))
        for k, a, c in specs
    )
    if len(canon) == 1:
        kind, a, c = canon[0]
        if kind == "dual":
            return ("dsym", a, c)
        if kind == "single":
            return ("sym", a)
    return ("specs", canon)


def expand_units(record: Dict) -> List[Tuple[Tuple, Tuple]]:
    """The comparable ``(key, value)`` units a record contributes to the
    decision map.  Keys are pure functions of (engine, node class,
    prefix digests, active digest) — order-independent across dispatch
    compositions; values are the decision at that node.  Diagnostic
    kinds contribute nothing."""
    kind = record.get("kind")
    eng = record.get("eng")
    act = record.get("act")
    if eng == "single":
        dig = record.get("dig")
        ln = record.get("len")
        if kind == "branch":
            syms = unb64(record["syms"])
            if len(syms) == 1:
                return [(("s", ln, dig, act), ("sym", syms[0]))]
            return [(("s", ln, dig, act), ("branch", tuple(sorted(syms))))]
        if kind == "run":
            out = []
            d = dig
            for i, s in enumerate(unb64(record["syms"])):
                out.append(((("s"), ln + i, d, act), ("sym", s)))
                d = crc_bytes(bytes([s]), d)
            return out
        return []
    if eng == "dual":
        cls = record.get("cls")
        l1, l2 = record.get("l1"), record.get("l2")
        d1, d2 = record.get("d1"), record.get("d2")
        if kind == "branch":
            value = _specs_value(record.get("specs", []))
            if cls == "p":
                return [(("p", l1, d1, act), value)]
            if value[0] == "sym":  # a dual node deciding one side only
                value = ("dsym", value[1], None)
            return [(("d", l1, l2, d1, d2, act), value)]
        if kind == "run":
            s1 = unb64(record.get("s1") or "")
            s2 = unb64(record.get("s2") or "")
            if cls == "p":
                out = []
                d = d1
                for i, s in enumerate(s1):
                    out.append((("p", l1 + i, d, act), ("sym", s)))
                    d = crc_bytes(bytes([s]), d)
                return out
            out = []
            for i in range(max(len(s1), len(s2))):
                a = s1[i] if i < len(s1) else None
                c = s2[i] if i < len(s2) else None
                out.append((("d", l1, l2, d1, d2, act), ("dsym", a, c)))
                if a is not None:
                    d1 = crc_bytes(bytes([a]), d1)
                    l1 += 1
                if c is not None:
                    d2 = crc_bytes(bytes([c]), d2)
                    l2 += 1
            return out
        return []
    return []


def _divergence_detail(rec_a, pop_a, rec_b, pop_b, key, va, vb) -> Dict:
    return {
        "pop_a": pop_a,
        "pop_b": pop_b,
        "key": list(key),
        "value_a": list(va),
        "value_b": list(vb),
        "record_a": rec_a,
        "record_b": rec_b,
        "prefix_len": rec_a.get("len", rec_a.get("l1")),
        "prefix_tail": rec_a.get("tail"),
    }


def diff_logs(
    records_a: List[Dict], records_b: List[Dict]
) -> Optional[Dict]:
    """First divergence between two record streams: build the decision
    map of B, scan A in emission order, report the first unit whose key
    exists in B with a different value.  One-sided keys are benign
    frontier differences and never reported.  ``None`` when the logs
    agree on every shared decision."""
    bmap: Dict[Tuple, Tuple] = {}
    for rec in records_b:
        for key, value in expand_units(rec):
            bmap.setdefault(key, (rec.get("pop"), value, rec))
    for rec in records_a:
        for key, value in expand_units(rec):
            hit = bmap.get(key)
            if hit is not None and hit[1] != value:
                return _divergence_detail(
                    rec, rec.get("pop"), hit[2], hit[0], key, value, hit[1]
                )
    return None


def load_log(path: str) -> List[Dict]:
    """Read one audit JSONL stream back into records."""
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def dump_parity_bundle(tag: str, out_dir: Optional[str] = None) -> Optional[str]:
    """Write the last two recorded searches + their first-divergence diff
    as a self-contained triage bundle under ``WAFFLE_AUDIT_DIR`` (the
    fuzz harness calls this when a parity assertion fails with audit
    enabled).  Returns the bundle path, or ``None`` when fewer than two
    recorded searches exist or no directory is available."""
    with _RECENT_LOCK:
        recent = list(_RECENT[-2:])
    if len(recent) < 2:
        return None
    base = out_dir or envspec.get_raw("WAFFLE_AUDIT_DIR", "")
    if not base:
        return None
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in tag)
    bundle = os.path.join(base, f"bundle-{safe}")
    try:
        os.makedirs(bundle, exist_ok=True)
        names = []
        for i, sink in enumerate(recent):
            name = f"log-{i}-{sink.engine}.jsonl"
            names.append(name)
            with open(os.path.join(bundle, name), "w") as fh:
                for rec in sink.records:
                    fh.write(json.dumps(rec) + "\n")
        diff = diff_logs(recent[0].records, recent[1].records)
        with open(os.path.join(bundle, "diff.json"), "w") as fh:
            json.dump({"tag": tag, "logs": names, "diff": diff}, fh,
                      indent=2, default=repr)
    except OSError:
        return None
    return bundle


# -- lockstep shadow execution ----------------------------------------


def _shadow_mode() -> str:
    override = getattr(_TLS, "shadow_override", None)
    if override is not None:
        return override
    return envspec.get_raw("WAFFLE_SHADOW", "").strip().lower()


@contextmanager
def shadow_override(mode: str):
    """Thread-locally force the shadow mode (the drill and tests use this
    instead of mutating the process environment)."""
    prev = getattr(_TLS, "shadow_override", None)
    _TLS.shadow_override = mode
    try:
        yield
    finally:
        _TLS.shadow_override = prev


class _LockstepComparator:
    """Streaming decision-map comparison between the primary ("a") and
    the shadow oracle ("b").  Each emitted record's units are checked
    against the other side's accumulated map; the first conflicting unit
    fires exactly one ``parity_divergence`` flight incident and raises
    :class:`ParityDivergence` in the feeding thread (the other side
    aborts at its next emit)."""

    def __init__(self, trace_id: Optional[str]) -> None:
        self._lock = lockcheck.make_lock("obs.audit.lockstep")
        self._maps: Dict[str, Dict[Tuple, Tuple]] = {"a": {}, "b": {}}
        self._trace_id = trace_id
        self.divergence: Optional[Dict] = None
        self.abort = False

    def feed(self, side: str, record: Dict) -> None:
        other = "b" if side == "a" else "a"
        units = expand_units(record)
        if side == "b":
            with _STATS_LOCK:
                _STATS["shadow_pops"] += 1
        with self._lock:
            if self.divergence is not None or self.abort:
                raise ParityDivergence(self.divergence or {"aborted": True})
            mine, theirs = self._maps[side], self._maps[other]
            for key, value in units:
                hit = theirs.get(key)
                if hit is not None and hit[1] != value:
                    if side == "a":
                        detail = _divergence_detail(
                            record, record.get("pop"), hit[2], hit[0],
                            key, value, hit[1],
                        )
                    else:
                        detail = _divergence_detail(
                            hit[2], hit[0], record, record.get("pop"),
                            key, hit[1], value,
                        )
                    self._signal(detail)
                    raise ParityDivergence(detail)
                mine[key] = (record.get("pop"), value, record)

    def final_mismatch(self, detail: Dict) -> None:
        with self._lock:
            if self.divergence is None:
                self._signal(detail)
        raise ParityDivergence(detail)

    def _signal(self, detail: Dict) -> None:
        # called with self._lock held; trigger once per comparator
        self.divergence = detail
        with _STATS_LOCK:
            _STATS["divergences"] += 1
        obs_flight.trigger(
            "parity_divergence", trace_id=self._trace_id, **detail
        )


class _ShadowRun:
    """One lockstep execution: the primary runs ``impl()`` in the caller
    thread, the python-oracle twin runs in a worker thread, both feeding
    the comparator."""

    def __init__(self, engine, engine_label: str) -> None:
        self.engine = engine
        self.label = engine_label
        self.comparator = _LockstepComparator(obs_trace.current_trace_id())
        self.shadow_engine = _clone_to_python(engine)
        self._shadow_results = None
        self._shadow_exc: Optional[BaseException] = None

    def _side_provider(self, side: str):
        def provider(engine_label: str) -> AuditSink:
            return AuditSink(
                engine_label,
                ring=_ring_cap(),
                on_emit=lambda rec: self.comparator.feed(side, rec),
                strict_align=True,
            )
        return provider

    def _shadow_body(self) -> None:
        _TLS.in_shadow = True
        _TLS.provider = self._side_provider("b")
        try:
            self._shadow_results = self.shadow_engine.consensus()
        except BaseException as exc:  # surfaced after join
            self._shadow_exc = exc
        finally:
            _TLS.provider = None
            _TLS.in_shadow = False

    def run(self, impl):
        thread = lockcheck.make_thread(
            target=self._shadow_body, name="waffle-shadow", daemon=True
        )
        prev = getattr(_TLS, "provider", None)
        _TLS.provider = self._side_provider("a")
        thread.start()
        try:
            results = impl()
        except BaseException:
            self.comparator.abort = True
            thread.join()
            raise
        finally:
            _TLS.provider = prev
        thread.join()
        if self.comparator.divergence is not None:
            raise ParityDivergence(self.comparator.divergence)
        if self._shadow_exc is not None:
            raise RuntimeError(
                "lockstep shadow oracle failed"
            ) from self._shadow_exc
        sig_a = [repr(r) for r in _as_list(results)]
        sig_b = [repr(r) for r in _as_list(self._shadow_results)]
        if sig_a != sig_b:
            self.comparator.final_mismatch({
                "pop_a": None, "pop_b": None, "key": ["final_results"],
                "value_a": sig_a[:4], "value_b": sig_b[:4],
                "record_a": {}, "record_b": {},
            })
        return results


def _as_list(results) -> List:
    if results is None:
        return []
    if isinstance(results, (list, tuple)):
        return list(results)
    return [results]


def _clone_to_python(engine):
    """A python-backend twin of ``engine`` with the same reads, offsets,
    and (deep-copied) pending restore state — built through the
    checkpoint config codec so every search-relevant knob survives."""
    from waffle_con_tpu.models import checkpoint as ckpt_mod

    cfg_dict = json.loads(json.dumps(ckpt_mod.encode_config_dict(engine.config)))
    cfg_dict["backend"] = "python"
    cfg = ckpt_mod.decode_config_dict(cfg_dict)
    shadow = type(engine)(cfg)
    for seq, off in zip(engine.sequences, engine.offsets):
        shadow.add_sequence_offset(seq, off)
    restore = getattr(engine, "_restore_state", None)
    if restore is not None:
        # the primary's impl consumes _restore_state; copy it first
        shadow._restore_state = json.loads(json.dumps(restore))
    return shadow


def maybe_shadow(engine, engine_label: str) -> Optional[_ShadowRun]:
    """A :class:`_ShadowRun` when lockstep shadow execution applies to
    this search, else ``None``.  Engages only for single/dual searches
    on a non-python primary backend, never recursively (the shadow
    thread's own search must not spawn a third engine)."""
    if getattr(_TLS, "in_shadow", False):
        return None
    if engine_label not in SHADOW_ENGINES:
        return None
    if _shadow_mode() != "python":
        return None
    backend = getattr(getattr(engine, "config", None), "backend", "python")
    if backend == "python":
        return None
    return _ShadowRun(engine, engine_label)


# -- dispatch-seam tap (construct_backend hook, TimedScorer-style) -----

#: scorer run ops the tap records (diagnostic records; no compared units)
_TAPPED_OPS = ("run_extend", "run_extend_dual", "run_arena", "run_mega")


class AuditScorerTap:
    """Transparent scorer proxy emitting one diagnostic ``dispatch``
    record per run-family dispatch into the current search's sink.  Like
    :class:`~waffle_con_tpu.obs.instrument.TimedScorer` it is invisible
    to capability feature-tests and only exists when auditing is on.
    It reads nothing from the dispatch result beyond the step count the
    engines already treat as a host scalar (never ``DeferredStats``
    fields — those fetch on access)."""

    def __init__(self, base, backend: str) -> None:
        self._base = base
        self._audit_backend = backend

    @property
    def counters(self):
        return self._base.counters

    @counters.setter
    def counters(self, value):
        self._base.counters = value

    def _wrap(self, name: str, fn):
        backend = self._audit_backend

        def tapped(*args, **kwargs):
            result = fn(*args, **kwargs)
            sink = getattr(_TLS, "current_sink", None)
            if sink is not None:
                steps = None
                if name != "run_arena" and isinstance(result, tuple) and result:
                    try:
                        steps = int(result[0])
                    except (TypeError, ValueError):
                        steps = None
                sink.emit({
                    "kind": "dispatch", "op": name, "backend": backend,
                    "steps": steps,
                })
            return result

        tapped.__name__ = name
        return tapped

    def __getattr__(self, name: str):
        base = self.__dict__["_base"]
        attr = getattr(base, name)
        if name not in _TAPPED_OPS or not callable(attr):
            return attr
        wrapped = self._wrap(name, attr)
        self.__dict__[name] = wrapped
        return wrapped


def maybe_tap(scorer, backend: str):
    """Wrap ``scorer`` in an :class:`AuditScorerTap` when auditing is
    enabled; return it unchanged otherwise (the zero-overhead decision,
    made once at backend construction)."""
    if audit_enabled():
        return AuditScorerTap(scorer, backend)
    return scorer
