"""Dispatch instrumentation: a transparent latency-recording scorer proxy.

:class:`TimedScorer` wraps a concrete backend scorer and times every
blocking dispatch, recording:

* ``waffle_dispatch_latency_seconds`` histogram per ``(backend, op)`` —
  the quantity the WFA-on-PIM / gpuPairHMM ports credit for finding that
  launch + transfer overhead, not the wavefront math, dominated;
* ``waffle_dispatch_total`` counter per ``(backend, op)``;
* ``waffle_dispatch_branches`` histogram per ``(backend, op)`` for the
  fused multi-branch dispatches (branches-per-dispatch is the batching
  win the ROADMAP's sharding work must not regress);
* ``waffle_handle_arena_live`` / ``waffle_handle_arena_capacity``
  gauges, sampled every few dispatches from the backend's
  ``live_handles()``;

and opens a ``dispatch:<op>`` tracer span (category ``dispatch``) so
host dispatches nest inside the engines' ``search`` spans in the Chrome
trace.

The proxy is only installed when observability is active (see
``construct_backend`` in :mod:`waffle_con_tpu.ops.scorer`); a disabled
run never pays for it.  It is deliberately transparent to the engines'
capability feature-tests: attribute access falls through to the wrapped
backend, so ``getattr(scorer, "run_extend", None)`` is ``None`` exactly
when the backend lacks the kernel.
"""

from __future__ import annotations

import time
from typing import Dict

from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import trace as obs_trace

#: dispatch method -> short op label (the same vocabulary as the scorer
#: counter keys and the supervisor's event ``op`` field)
TIMED_OPS: Dict[str, str] = {
    "root": "root",
    "push": "push",
    "push_many": "push",
    "stats": "stats",
    "clone": "clone",
    "clone_many": "clone",
    "clone_push_many": "clone_push",
    "activate": "activate",
    "deactivate": "activate",
    "deactivate_many": "activate",
    "finalized_eds": "finalize",
    "best_activation_offset": "offset_scan",
    "run_extend": "run",
    "run_extend_dual": "run_dual",
    "run_arena": "arena",
}

#: ops whose first positional argument is a spec list (fused dispatches)
_BATCHED_OPS = frozenset(
    {"push_many", "clone_many", "clone_push_many", "deactivate_many"}
)

#: sample the handle-arena occupancy gauge every this many dispatches
_GAUGE_SAMPLE_EVERY = 16


class TimedScorer:
    """Latency/trace-recording proxy over a concrete backend scorer."""

    def __init__(self, base, backend: str) -> None:
        self._base = base
        self._backend = backend
        self._calls_since_gauge = 0

    # ``counters`` must stay a live view of the backend's dict in BOTH
    # directions: the supervisor swaps in a shared dict via plain
    # attribute assignment (``scorer.counters = ...``) and the backend's
    # own increments must land in whatever dict is current.
    @property
    def counters(self):
        return self._base.counters

    @counters.setter
    def counters(self, value):
        self._base.counters = value

    @property
    def timed_backend(self) -> str:
        """The backend label this proxy records under."""
        return self._backend

    def _sample_arena_gauge(self) -> None:
        live_handles = getattr(self._base, "live_handles", None)
        if live_handles is None:
            return
        live, capacity = live_handles()
        reg = obs_metrics.registry()
        reg.gauge("waffle_handle_arena_live", backend=self._backend).set(live)
        if capacity is not None:
            reg.gauge(
                "waffle_handle_arena_capacity", backend=self._backend
            ).set(capacity)

    def _wrap(self, name: str, op: str, fn):
        backend = self._backend
        batched = name in _BATCHED_OPS
        span = obs_trace.span

        def timed(*args, **kwargs):
            metrics_on = obs_metrics.metrics_enabled()
            with span(f"dispatch:{op}", "dispatch", backend=backend):
                t0 = time.perf_counter()
                try:
                    return fn(*args, **kwargs)
                finally:
                    if metrics_on:
                        dt = time.perf_counter() - t0
                        reg = obs_metrics.registry()
                        reg.histogram(
                            "waffle_dispatch_latency_seconds",
                            backend=backend, op=op,
                        ).observe(dt)
                        reg.counter(
                            "waffle_dispatch_total", backend=backend, op=op
                        ).inc()
                        if batched and args:
                            reg.histogram(
                                "waffle_dispatch_branches",
                                buckets=obs_metrics.DEFAULT_COUNT_BUCKETS,
                                backend=backend, op=op,
                            ).observe(len(args[0]))
                        self._calls_since_gauge += 1
                        if self._calls_since_gauge >= _GAUGE_SAMPLE_EVERY:
                            self._calls_since_gauge = 0
                            self._sample_arena_gauge()

        timed.__name__ = name
        return timed

    def __getattr__(self, name: str):
        # normal lookup failed: delegate to the backend, wrapping timed
        # dispatch methods once and caching the wrapper on the instance
        # (instance-dict hits skip __getattr__ on every later access)
        base = self.__dict__["_base"]
        attr = getattr(base, name)
        op = TIMED_OPS.get(name)
        if op is None or not callable(attr):
            return attr
        wrapped = self._wrap(name, op, attr)
        self.__dict__[name] = wrapped
        return wrapped


def maybe_instrument(scorer, backend: str):
    """Wrap ``scorer`` in a :class:`TimedScorer` when observability is
    active; return it unchanged otherwise."""
    if obs_metrics.metrics_enabled() or obs_trace.tracing_enabled():
        return TimedScorer(scorer, backend)
    return scorer
