"""Dispatch instrumentation: a transparent latency-recording scorer proxy.

:class:`TimedScorer` wraps a concrete backend scorer and times every
blocking dispatch, recording:

* ``waffle_dispatch_latency_seconds`` histogram per ``(backend, op)`` —
  the quantity the WFA-on-PIM / gpuPairHMM ports credit for finding that
  launch + transfer overhead, not the wavefront math, dominated;
* ``waffle_dispatch_total`` counter per ``(backend, op)``;
* ``waffle_dispatch_branches`` histogram per ``(backend, op)`` for the
  fused multi-branch dispatches (branches-per-dispatch is the batching
  win the ROADMAP's sharding work must not regress);
* ``waffle_handle_arena_live`` / ``waffle_handle_arena_capacity``
  gauges, sampled every few dispatches from the backend's
  ``live_handles()``;

and opens a ``dispatch:<op>`` tracer span (category ``dispatch``) so
host dispatches nest inside the engines' ``search`` spans in the Chrome
trace.

With profiling on (``WAFFLE_PROFILE=1`` /
:func:`~waffle_con_tpu.obs.phases.enable_profiling`) every timed
dispatch additionally opens a :mod:`~waffle_con_tpu.obs.phases`
record, which the dispatch seam (``ops/jax_scorer.py`` /
``ops/ragged.py``) fills with the host-prep / device-compute /
transfer / host-post breakdown and kernel/K/geometry labels.

The proxy is only installed when observability is active (see
``construct_backend`` in :mod:`waffle_con_tpu.ops.scorer`); a disabled
run never pays for it.  It is deliberately transparent to the engines'
capability feature-tests: attribute access falls through to the wrapped
backend, so ``getattr(scorer, "run_extend", None)`` is ``None`` exactly
when the backend lacks the kernel.

:class:`FrontierSampler` is the search-frontier telemetry half: a
decimated per-pop sampler the engines feed (queue depth, live branch
count, best-vs-frontier cost gap, speculative commit rate, ragged
injections) that writes ``frontier`` records into the always-on flight
ring — ``bench.py --explain`` dumps them as a timeline.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import phases as obs_phases
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.utils import envspec

#: dispatch method -> short op label (the same vocabulary as the scorer
#: counter keys and the supervisor's event ``op`` field)
TIMED_OPS: Dict[str, str] = {
    "root": "root",
    "push": "push",
    "push_many": "push",
    "stats": "stats",
    "clone": "clone",
    "clone_many": "clone",
    "clone_push_many": "clone_push",
    "activate": "activate",
    "deactivate": "activate",
    "deactivate_many": "activate",
    "finalized_eds": "finalize",
    "best_activation_offset": "offset_scan",
    "run_extend": "run",
    "run_extend_dual": "run_dual",
    "run_arena": "arena",
}

#: ops whose first positional argument is a spec list (fused dispatches)
_BATCHED_OPS = frozenset(
    {"push_many", "clone_many", "clone_push_many", "deactivate_many"}
)

#: sample the handle-arena occupancy gauge every this many dispatches
_GAUGE_SAMPLE_EVERY = 16


class TimedScorer:
    """Latency/trace-recording proxy over a concrete backend scorer."""

    def __init__(self, base, backend: str) -> None:
        self._base = base
        self._backend = backend
        self._calls_since_gauge = 0

    # ``counters`` must stay a live view of the backend's dict in BOTH
    # directions: the supervisor swaps in a shared dict via plain
    # attribute assignment (``scorer.counters = ...``) and the backend's
    # own increments must land in whatever dict is current.
    @property
    def counters(self):
        return self._base.counters

    @counters.setter
    def counters(self, value):
        self._base.counters = value

    @property
    def timed_backend(self) -> str:
        """The backend label this proxy records under."""
        return self._backend

    def _sample_arena_gauge(self) -> None:
        live_handles = getattr(self._base, "live_handles", None)
        if live_handles is None:
            return
        live, capacity = live_handles()
        reg = obs_metrics.registry()
        reg.gauge("waffle_handle_arena_live", backend=self._backend).set(live)
        if capacity is not None:
            reg.gauge(
                "waffle_handle_arena_capacity", backend=self._backend
            ).set(capacity)

    def _wrap(self, name: str, op: str, fn):
        backend = self._backend
        batched = name in _BATCHED_OPS
        span = obs_trace.span

        def timed(*args, **kwargs):
            metrics_on = obs_metrics.metrics_enabled()
            # phase record: the dispatch seam attributes device/
            # transfer time into it; one boolean check when profiling
            # is off
            rec = obs_phases.begin(op, backend)
            with span(f"dispatch:{op}", "dispatch", backend=backend):
                t0 = time.perf_counter()
                try:
                    return fn(*args, **kwargs)
                finally:
                    obs_phases.end(rec)
                    if metrics_on:
                        dt = time.perf_counter() - t0
                        reg = obs_metrics.registry()
                        reg.histogram(
                            "waffle_dispatch_latency_seconds",
                            backend=backend, op=op,
                        ).observe(dt)
                        reg.counter(
                            "waffle_dispatch_total", backend=backend, op=op
                        ).inc()
                        if batched and args:
                            reg.histogram(
                                "waffle_dispatch_branches",
                                buckets=obs_metrics.DEFAULT_COUNT_BUCKETS,
                                backend=backend, op=op,
                            ).observe(len(args[0]))
                        self._calls_since_gauge += 1
                        if self._calls_since_gauge >= _GAUGE_SAMPLE_EVERY:
                            self._calls_since_gauge = 0
                            self._sample_arena_gauge()

        timed.__name__ = name
        return timed

    def __getattr__(self, name: str):
        # normal lookup failed: delegate to the backend, wrapping timed
        # dispatch methods once and caching the wrapper on the instance
        # (instance-dict hits skip __getattr__ on every later access)
        base = self.__dict__["_base"]
        attr = getattr(base, name)
        op = TIMED_OPS.get(name)
        if op is None or not callable(attr):
            return attr
        wrapped = self._wrap(name, op, attr)
        self.__dict__[name] = wrapped
        return wrapped


def maybe_instrument(scorer, backend: str):
    """Wrap ``scorer`` in a :class:`TimedScorer` when observability is
    active (metrics, tracing, or phase profiling); return it unchanged
    otherwise."""
    if (
        obs_metrics.metrics_enabled()
        or obs_trace.tracing_enabled()
        or obs_phases.profiling_enabled()
    ):
        return TimedScorer(scorer, backend)
    return scorer


#: default pop decimation of the frontier sampler; one record per this
#: many queue pops (``WAFFLE_FRONTIER_SAMPLE`` overrides; 0 disables)
FRONTIER_SAMPLE_DEFAULT = 64


def _frontier_interval() -> int:
    env = envspec.get_raw("WAFFLE_FRONTIER_SAMPLE", "")
    if env == "":
        return FRONTIER_SAMPLE_DEFAULT
    try:
        return max(0, int(env))
    except ValueError:
        return FRONTIER_SAMPLE_DEFAULT


class FrontierSampler:
    """Decimated per-pop search-frontier telemetry.

    One per search; the engine pop loops call :meth:`due` every pop (a
    modulo on an int — the always-on cost) and, when it fires,
    :meth:`sample` with whatever frontier state is in hand.  Each
    sample is ONE flight-ring record (kind ``frontier``): pop count,
    queue depth, live branch count, best-vs-next cost gap, consensus
    progress, cumulative speculative commit rate, and ragged-injection
    count — the timeline ``bench.py --explain`` renders, and the
    context an incident dump carries when a search goes pathological.
    """

    __slots__ = ("engine", "interval", "_t0", "_n")

    def __init__(self, engine_label: str) -> None:
        self.engine = engine_label
        self.interval = _frontier_interval()
        self._t0 = time.perf_counter()
        self._n = 0

    def due(self, pops: int) -> bool:
        return self.interval > 0 and pops % self.interval == 0

    def sample(
        self,
        pops: int,
        queue_depth: int,
        live_branches: int,
        top_cost: int,
        next_cost: Optional[int],
        top_len: int,
        farthest: int,
        counters: Optional[Dict[str, int]] = None,
        gang_width: Optional[int] = None,
    ) -> None:
        self._n += 1
        fields = {
            "engine": self.engine,
            "t_s": round(time.perf_counter() - self._t0, 4),
            "pops": int(pops),
            "queue": int(queue_depth),
            "live": int(live_branches),
            "top_cost": int(top_cost),
            "gap": (
                int(next_cost) - int(top_cost)
                if next_cost is not None else None
            ),
            "top_len": int(top_len),
            "farthest": int(farthest),
        }
        if counters:
            spec = (
                counters.get("run_spec_cols", 0)
                + counters.get("run_dual_spec_cols", 0)
            )
            committed = (
                counters.get("run_steps", 0)
                + counters.get("run_dual_steps", 0)
            )
            fields["spec_commit_rate"] = (
                round(committed / spec, 4) if spec else None
            )
            fields["ragged_injected"] = counters.get(
                "run_ragged_injected", 0
            )
            gi = counters.get("run_gang_injected", 0)
            gm = counters.get("run_gang_mispredict", 0)
            fields["gang_commit_rate"] = (
                round(gi / (gi + gm), 4) if (gi + gm) else None
            )
            # megastep run lengths: committed symbols per mega dispatch
            # (the quantity the megastep optimizes — long unambiguous
            # stretches swallowed under one bundled round trip), plus
            # the cumulative blocking-sync count the search has paid
            mc = counters.get("run_mega_calls", 0)
            fields["mega_calls"] = mc
            fields["mega_syms_per_dispatch"] = (
                round(counters.get("run_mega_steps", 0) / mc, 2)
                if mc else None
            )
            fields["host_round_trips"] = counters.get(
                "host_round_trips", 0
            )
        if gang_width is not None:
            fields["gang_width"] = int(gang_width)
        obs_flight.record(
            "frontier", trace_id=obs_trace.current_trace_id(), **fields
        )

    @property
    def samples_taken(self) -> int:
        return self._n
