"""Always-on flight recorder: bounded in-memory ring + incident dumps.

Post-hoc debuggability for the serving stack: when one of many
concurrent jobs blows its deadline, demotes a backend, or trips the
admission queue, the operator needs that job's recent timeline *without
having pre-enabled tracing*.  The recorder therefore runs always-on and
lock-cheap — a fixed-size ``collections.deque`` ring of pre-rendered
tuples (``deque.append`` with ``maxlen`` is atomic under the GIL, so
the hot recording path takes no lock and allocates one small tuple per
record) — and only does real work when an **anomaly trigger** fires.

Triggers (see :data:`TRIGGER_REASONS`): ``deadline_exceeded``,
``backend_demoted``, ``cache_quarantine``, ``service_overloaded``,
``watchdog_budget_exceeded``, the SLO layer's ``slow_search``
(current search > k× rolling p95, :mod:`waffle_con_tpu.obs.slo`), and
the out-of-process front door's ``worker_lost`` (a worker process
crashed or went silent past the liveness lapse,
:mod:`waffle_con_tpu.serve.procs.door`).

On a trigger the recorder assembles a self-contained JSON **incident**:
the triggering job's records (filtered from the ring by trace id),
the recent ring tail, the runtime event log, a metrics snapshot (when
metrics are on), and the rolling SLO snapshot.  With
``WAFFLE_FLIGHT_DIR`` set the incident is also written to
``<dir>/incident-<seq>-<reason>.json`` (atomic rename); unset, incidents
stay in memory only (:meth:`FlightRecorder.incidents`) so test and
library runs never litter the working directory.

Incidents are deduplicated on ``(reason, trace_id)`` within a rolling
time window — a retry storm produces one dump, not hundreds, but a
RECURRING incident re-fires once the window expires (a suppressed-
forever dedupe hid every recurrence after the first).
``WAFFLE_FLIGHT_DEDUPE_S`` sets the window (default 300 s; ``0``
disables dedupe entirely).  ``WAFFLE_FLIGHT_RING`` sizes the ring
(default 2048 records).

Overhead contract: the microbench/raw-dispatch path makes **zero**
calls into this module (recording happens at serve-layer dispatch and
job boundaries, anomaly sites, and the engines' frontier sampler —
which is decimated to one record per ``WAFFLE_FRONTIER_SAMPLE`` queue
pops, 0 to disable), so the 620 steps/s hot-loop floor is unaffected
by construction; a record is one deque append.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec

#: every reason :func:`trigger` is called with somewhere in the codebase
TRIGGER_REASONS = (
    "deadline_exceeded",
    "backend_demoted",
    "cache_quarantine",
    "service_overloaded",
    "watchdog_budget_exceeded",
    "slow_search",
    "worker_lost",
    "checkpoint_rejected",
    "parity_divergence",
)

DEFAULT_RING_SIZE = 2048
#: in-memory incident cap (dumped files are bounded by dedupe instead)
MAX_INCIDENTS = 64
INCIDENT_SCHEMA = "waffle-flight-incident/1"
#: default (reason, trace_id) dedupe window in seconds
DEFAULT_DEDUPE_S = 300.0


def _ring_size() -> int:
    try:
        return max(16, int(envspec.get_raw("WAFFLE_FLIGHT_RING", "") or
                           DEFAULT_RING_SIZE))
    except ValueError:
        return DEFAULT_RING_SIZE


def _dedupe_window_s() -> float:
    try:
        env = envspec.get_raw("WAFFLE_FLIGHT_DEDUPE_S", "")
        return float(env) if env != "" else DEFAULT_DEDUPE_S
    except ValueError:
        return DEFAULT_DEDUPE_S


def _jsonable(value):
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    return repr(value)


class FlightRecorder:
    """Bounded ring of recent records plus incident assembly/dump."""

    def __init__(self, ring_size: Optional[int] = None,
                 dedupe_s: Optional[float] = None) -> None:
        self._ring: "collections.deque[Tuple]" = collections.deque(
            maxlen=ring_size or _ring_size()
        )
        self._lock = lockcheck.make_lock("obs.flight.FlightRecorder")
        #: (reason, trace_id) -> last fire timestamp; entries older
        #: than the dedupe window expire, so a RECURRING incident
        #: re-fires (constructor arg pins the window for tests; None
        #: re-reads WAFFLE_FLIGHT_DEDUPE_S per trigger)
        self._seen: Dict[Tuple[str, Optional[str]], float] = {}
        self._dedupe_s = dedupe_s
        self._seq = 0
        self._incidents: List[Dict] = []

    # -- hot path ------------------------------------------------------

    def record(self, kind: str, /, trace_id: Optional[str] = None,
               **fields) -> None:
        """Append one pre-rendered record to the ring (no lock: deque
        append with ``maxlen`` is atomic).  ``kind`` is positional-only
        so callers may carry a ``kind=...`` field of their own."""
        self._ring.append(
            (time.time(), kind, trace_id, tuple(fields.items()))
        )

    # -- reads ---------------------------------------------------------

    def records(self, trace_id: Optional[str] = None,
                limit: Optional[int] = None) -> List[Dict]:
        """Point-in-time copy of the ring as dicts, oldest first,
        optionally filtered to one trace and/or tail-limited."""
        snap = list(self._ring)
        if trace_id is not None:
            snap = [r for r in snap if r[2] == trace_id]
        if limit is not None:
            snap = snap[-limit:]
        return [
            {**dict(fields), "ts": ts, "kind": kind, "trace_id": tid}
            for ts, kind, tid, fields in snap
        ]

    def incidents(self) -> List[Dict]:
        with self._lock:
            return [dict(i) for i in self._incidents]

    # -- anomaly path --------------------------------------------------

    def _admit(self, reason: str,
               trace_id: Optional[str]) -> Optional[int]:
        """Dedupe on ``(reason, trace_id)`` and allocate a sequence
        number; ``None`` means suppressed within the rolling window."""
        key = (reason, trace_id)
        window = (
            self._dedupe_s if self._dedupe_s is not None
            else _dedupe_window_s()
        )
        now = time.time()
        with self._lock:
            last = self._seen.get(key)
            if last is not None and window > 0 and now - last < window:
                return None
            self._seen[key] = now
            if len(self._seen) > 4 * MAX_INCIDENTS:
                # bound the dedupe table: expired entries are dead
                # weight once their window passed
                self._seen = {
                    k: t for k, t in self._seen.items()
                    if now - t < window
                }
            self._seq += 1
            return self._seq

    def _dump_and_keep(self, incident: Dict, seq: int,
                       reason: str) -> Dict:
        """Write the incident to ``WAFFLE_FLIGHT_DIR`` (atomic rename,
        when set) and append it to the in-memory list."""
        dump_dir = envspec.get_raw("WAFFLE_FLIGHT_DIR", "")
        if dump_dir:
            try:
                os.makedirs(dump_dir, exist_ok=True)
                path = os.path.join(
                    dump_dir, f"incident-{seq:04d}-{reason}.json"
                )
                tmp = f"{path}.tmp-{os.getpid()}"
                with open(tmp, "w") as fh:
                    json.dump(incident, fh, indent=1, default=repr)
                os.replace(tmp, path)
                incident["path"] = path
            except OSError:
                # a full/readonly dump dir must never take down serving;
                # the incident still lands in memory below
                incident["path"] = None
        with self._lock:
            self._incidents.append(incident)
            del self._incidents[:-MAX_INCIDENTS]
        return incident

    def trigger(self, reason: str, trace_id: Optional[str] = None,
                **detail) -> Optional[Dict]:
        """Fire an anomaly trigger: assemble an incident (and dump it to
        ``WAFFLE_FLIGHT_DIR`` when set).  Returns the incident dict, or
        ``None`` when ``(reason, trace_id)`` fired within the dedupe
        window (``WAFFLE_FLIGHT_DEDUPE_S``, default 300 s; expired
        entries re-fire so recurring incidents stay visible)."""
        seq = self._admit(reason, trace_id)
        if seq is None:
            return None
        incident = self._build_incident(seq, reason, trace_id, detail)
        return self._dump_and_keep(incident, seq, reason)

    def ingest_remote(self, incident: Dict,
                      worker: Optional[str] = None) -> Optional[Dict]:
        """Re-ingest an incident built by ANOTHER process's recorder
        (a worker's INCIDENT frame): run this side's
        ``(reason, trace_id)`` dedupe at fleet scope, re-stamp the
        sequence number, attribute the originating worker, and dump via
        the normal path.  Returns the ingested incident, or ``None``
        when suppressed (or the payload is not an incident object)."""
        if not isinstance(incident, dict):
            return None
        reason = str(incident.get("reason") or "unknown")
        trace_id = incident.get("trace_id")
        if trace_id is not None:
            trace_id = str(trace_id)
        seq = self._admit(reason, trace_id)
        if seq is None:
            return None
        ingested = dict(incident)
        ingested["seq"] = seq
        ingested["reason"] = reason
        ingested["origin"] = "remote"
        ingested["ingested_unix_time"] = time.time()
        if worker is not None:
            ingested["worker"] = worker
        # the shipped path (if any) names a file in the WORKER's dump
        # dir; keep it as provenance and let _dump_and_keep set this
        # side's path
        if "path" in ingested:
            ingested["worker_path"] = ingested.pop("path")
        return self._dump_and_keep(ingested, seq, reason)

    def _build_incident(self, seq: int, reason: str,
                        trace_id: Optional[str], detail: Dict) -> Dict:
        from waffle_con_tpu.obs import metrics as obs_metrics
        from waffle_con_tpu.obs import slo as obs_slo
        from waffle_con_tpu.runtime import events as runtime_events

        incident: Dict = {
            "schema": INCIDENT_SCHEMA,
            "seq": seq,
            "reason": reason,
            "trace_id": trace_id,
            "unix_time": time.time(),
            "detail": {str(k): _jsonable(v) for k, v in detail.items()},
            "trace": self.records(trace_id=trace_id) if trace_id else [],
            "recent": self.records(limit=256),
            "events": runtime_events.get_events()[-256:],
            "slo": obs_slo.snapshot(),
        }
        if obs_metrics.metrics_enabled():
            incident["metrics"] = obs_metrics.registry().snapshot()
        return incident

    def reset(self) -> None:
        """Drop ring, dedupe state, and in-memory incidents (tests)."""
        with self._lock:
            self._ring.clear()
            self._seen.clear()
            self._incidents.clear()
            self._seq = 0


_RECORDER = FlightRecorder()

#: trigger listeners: called with (reason, trace_id, detail) on EVERY
#: module-level trigger, BEFORE dedupe — health consumers (the replica
#: front door's shedding logic) need each occurrence, not each unique
#: incident.  Exceptions are swallowed: a broken listener must never
#: take down the anomaly path.
_LISTENERS: List = []
_LISTENER_LOCK = lockcheck.make_lock("obs.flight.LISTENERS")


def add_trigger_listener(fn) -> None:
    """Register ``fn(reason, trace_id, detail)`` on every trigger."""
    with _LISTENER_LOCK:
        if fn not in _LISTENERS:
            _LISTENERS.append(fn)


def remove_trigger_listener(fn) -> None:
    with _LISTENER_LOCK:
        try:
            _LISTENERS.remove(fn)
        except ValueError:
            pass


def _notify_listeners(reason: str, trace_id: Optional[str],
                      detail: Dict) -> None:
    with _LISTENER_LOCK:
        listeners = list(_LISTENERS)
    for fn in listeners:
        try:
            fn(reason, trace_id, detail)
        except Exception:  # noqa: BLE001 - listeners must never break
            pass


#: incident listeners: called with the fully-built incident dict AFTER
#: dedupe admitted it — the proc worker forwards these to the door as
#: INCIDENT frames (one frame per unique incident, not per occurrence).
_INCIDENT_LISTENERS: List = []


def add_incident_listener(fn) -> None:
    """Register ``fn(incident)`` on every post-dedupe built incident."""
    with _LISTENER_LOCK:
        if fn not in _INCIDENT_LISTENERS:
            _INCIDENT_LISTENERS.append(fn)


def remove_incident_listener(fn) -> None:
    with _LISTENER_LOCK:
        try:
            _INCIDENT_LISTENERS.remove(fn)
        except ValueError:
            pass


def _notify_incident_listeners(incident: Dict) -> None:
    with _LISTENER_LOCK:
        listeners = list(_INCIDENT_LISTENERS)
    for fn in listeners:
        try:
            fn(incident)
        except Exception:  # noqa: BLE001 - listeners must never break
            pass


def get_recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, /, trace_id: Optional[str] = None, **fields) -> None:
    _RECORDER.record(kind, trace_id=trace_id, **fields)


def trigger(reason: str, trace_id: Optional[str] = None,
            **detail) -> Optional[Dict]:
    _notify_listeners(reason, trace_id, detail)
    incident = _RECORDER.trigger(reason, trace_id=trace_id, **detail)
    if incident is not None:
        _notify_incident_listeners(incident)
    return incident


def ingest_remote(incident: Dict,
                  worker: Optional[str] = None) -> Optional[Dict]:
    """Module-level :meth:`FlightRecorder.ingest_remote` passthrough."""
    return _RECORDER.ingest_remote(incident, worker=worker)


def incidents() -> List[Dict]:
    return _RECORDER.incidents()


def reset() -> None:
    _RECORDER.reset()
