"""Phase-attributed dispatch profiling: where did each dispatch go?

The dispatch latency histograms (:mod:`waffle_con_tpu.obs.instrument`)
answer *how long* each dispatch took; this module answers *where the
time went* inside one.  Every profiled dispatch is split into four
phases — the decomposition gpuPairHMM uses to find the next kernel
bottleneck (PAPERS.md):

* ``host_prep`` — host-side argument marshalling before the first
  device interaction (param arrays, table padding, slot bookkeeping);
* ``device_compute`` — kernel execution, measured exactly by fencing
  the dispatched arrays with ``jax.block_until_ready`` while a record
  is active (profiling inserts the fence; an unprofiled run never
  blocks early);
* ``transfer`` — device→host result movement (``jax.device_get``),
  including a :class:`~waffle_con_tpu.ops.scorer.DeferredStats`
  resolve that lands after the dispatch returned;
* ``host_post`` — the remainder: result decode, counter bookkeeping,
  numpy reshaping between the last device interaction and the
  dispatch's return.

Records are labeled by kernel family (``solo`` / ``dual`` / ``arena``
/ ``ragged`` / ``pallas`` / ``other``), speculative block size ``K``
(``WAFFLE_RUN_COLS``), and a geometry bucket (``B<br>R<reads>W<band>``)
so one run's profile separates the north-star geometry from the small
fixtures sharing the process.

Enabling: ``WAFFLE_PROFILE=1`` or :func:`enable_profiling`.  The
zero-overhead-when-disabled contract matches the tracer's: with
profiling off, :func:`begin` returns ``None`` after one boolean check
and no phase scope allocates anything.  Profiling is independent of
metrics — phase totals always aggregate process-wide (for
``SearchReport`` / bench evidence); labeled histograms are published
only when metrics are ALSO on.

Conservation property (tested): for an eagerly-synced dispatch
(``WAFFLE_ASYNC_SYNC=0``) the four phases sum to the dispatch wall
time exactly, because ``host_prep`` is measured, ``device_compute``
and ``transfer`` are measured, and ``host_post`` is defined as the
remainder.  A deferred resolve after close is accounted as late
``transfer`` in the aggregate (and flagged ``late`` on the record).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec

PHASES = ("host_prep", "device_compute", "transfer", "host_post")

#: kernel-family vocabulary for the ``kernel`` label (``mega`` = the
#: megastep run entries — M blocks of K columns per device iteration)
KERNEL_FAMILIES = (
    "solo", "dual", "arena", "ragged", "pallas", "mega", "other"
)

#: bounded ring of recently closed records kept for introspection/tests
_RECENT_MAX = 256

#: programmatic override; None defers to the WAFFLE_PROFILE env var
_FORCED: Optional[bool] = None


def profiling_enabled() -> bool:
    """Whether dispatches should record phase breakdowns
    (``WAFFLE_PROFILE`` env, or a programmatic
    :func:`enable_profiling` override)."""
    if _FORCED is not None:
        return _FORCED
    return envspec.flag("WAFFLE_PROFILE")


def enable_profiling(on: bool = True) -> None:
    """Programmatic enable/disable (overrides the env var)."""
    global _FORCED
    _FORCED = bool(on)


def reset_profiling_enabled() -> None:
    """Drop the programmatic override; the env var rules again."""
    global _FORCED
    _FORCED = None


class DispatchRecord:
    """Phase accounting for ONE dispatch.

    Built by :func:`begin`, closed by :func:`end`.  The dispatch seam
    (``ops/jax_scorer.py`` / ``ops/ragged.py``) attributes device and
    transfer time into the active record via :func:`device_scope` /
    :func:`transfer_scope` and labels it via :meth:`annotate`;
    ``host_prep`` is everything before the first attributed phase and
    ``host_post`` is the unattributed remainder at close."""

    __slots__ = (
        "op", "backend", "kernel", "k", "geom", "t0", "device_s",
        "transfer_s", "t_first_phase", "wall_s", "closed", "late",
    )

    def __init__(self, op: str, backend: str) -> None:
        self.op = op
        self.backend = backend
        self.kernel = "other"
        self.k = 1
        self.geom = ""
        self.device_s = 0.0
        self.transfer_s = 0.0
        self.t_first_phase: Optional[float] = None
        self.wall_s = 0.0
        self.closed = False
        self.late = False
        self.t0 = time.perf_counter()

    def annotate(self, kernel: Optional[str] = None,
                 k: Optional[int] = None,
                 geom: Optional[str] = None) -> None:
        if kernel is not None:
            self.kernel = kernel
        if k is not None:
            self.k = int(k)
        if geom is not None:
            self.geom = geom

    def add_device(self, seconds: float, when: float) -> None:
        if self.t_first_phase is None:
            self.t_first_phase = when
        self.device_s += seconds

    def add_transfer(self, seconds: float, when: float) -> None:
        if self.t_first_phase is None:
            self.t_first_phase = when
        self.transfer_s += seconds
        if self.closed:
            # a DeferredStats resolved after the dispatch returned:
            # publish the late transfer into the aggregate (the wall
            # time of the ORIGINAL dispatch is already final)
            self.late = True
            _publish_phase(self, "transfer", seconds)

    def phases(self) -> Dict[str, float]:
        """The four-phase breakdown (closed records only)."""
        prep = (
            (self.t_first_phase - self.t0)
            if self.t_first_phase is not None else 0.0
        )
        post = max(
            0.0, self.wall_s - prep - self.device_s - self.transfer_s
        )
        return {
            "host_prep": prep,
            "device_compute": self.device_s,
            "transfer": self.transfer_s,
            "host_post": post,
        }

    def to_dict(self) -> Dict:
        out = {
            "op": self.op,
            "backend": self.backend,
            "kernel": self.kernel,
            "k": self.k,
            "geom": self.geom,
            "wall_s": self.wall_s,
            "late": self.late,
        }
        out.update(self.phases())
        return out


#: the dispatch currently being profiled on this thread (dispatches
#: never nest: the engines issue one blocking scorer call at a time)
_ACTIVE = threading.local()

_agg_lock = lockcheck.make_lock("obs.phases.AGG")
#: (kernel, op, k, geom) -> {phase: seconds, "count": n, "wall_s": s}
_agg: Dict[Tuple[str, str, int, str], Dict[str, float]] = {}
_recent: List[DispatchRecord] = []


def begin(op: str, backend: str) -> Optional[DispatchRecord]:
    """Open a phase record for one dispatch; returns ``None`` (fast)
    when profiling is disabled or another record is already active on
    this thread (re-entrant proxy layers profile the OUTERMOST call)."""
    if not profiling_enabled():
        return None
    if getattr(_ACTIVE, "record", None) is not None:
        return None
    rec = DispatchRecord(op, backend)
    _ACTIVE.record = rec
    return rec


def end(rec: Optional[DispatchRecord]) -> None:
    """Close a record opened by :func:`begin` and publish it."""
    if rec is None:
        return
    rec.wall_s = time.perf_counter() - rec.t0
    rec.closed = True
    if getattr(_ACTIVE, "record", None) is rec:
        _ACTIVE.record = None
    phases = rec.phases()
    key = (rec.kernel, rec.op, rec.k, rec.geom)
    with _agg_lock:
        slot = _agg.get(key)
        if slot is None:
            slot = {p: 0.0 for p in PHASES}
            slot["count"] = 0
            slot["wall_s"] = 0.0
            _agg[key] = slot
        for p in PHASES:
            slot[p] += phases[p]
        slot["count"] += 1
        slot["wall_s"] += rec.wall_s
        _recent.append(rec)
        del _recent[:-_RECENT_MAX]
    _publish_histograms(rec, phases)


def current() -> Optional[DispatchRecord]:
    """The active record on this thread (the dispatch seam's hook)."""
    return getattr(_ACTIVE, "record", None)


class _PhaseScope:
    """Context manager attributing its elapsed time to one phase of
    ``rec``; reusable closure-free object so the enabled path is two
    ``perf_counter`` calls and one float add."""

    __slots__ = ("_rec", "_add", "_t0")

    def __init__(self, rec: DispatchRecord, add) -> None:
        self._rec = rec
        self._add = add

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        now = time.perf_counter()
        self._add(now - self._t0, self._t0)
        return False


class _NullScope:
    """Shared no-op scope: the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SCOPE = _NullScope()


def device_scope(rec: Optional[DispatchRecord]):
    """Scope whose elapsed time is the dispatch's device-compute phase
    (wrap the kernel call + ``block_until_ready`` fence)."""
    if rec is None:
        return NULL_SCOPE
    return _PhaseScope(rec, rec.add_device)


def transfer_scope(rec: Optional[DispatchRecord]):
    """Scope whose elapsed time is device→host transfer
    (wrap ``jax.device_get``)."""
    if rec is None:
        return NULL_SCOPE
    return _PhaseScope(rec, rec.add_transfer)


def _publish_phase(rec: DispatchRecord, phase: str,
                   seconds: float) -> None:
    """Fold a late (post-close) phase contribution into the aggregate
    and, when metrics are on, the labeled histogram."""
    key = (rec.kernel, rec.op, rec.k, rec.geom)
    with _agg_lock:
        slot = _agg.get(key)
        if slot is not None:
            slot[phase] += seconds
    try:
        from waffle_con_tpu.obs import metrics as obs_metrics

        if obs_metrics.metrics_enabled():
            obs_metrics.registry().histogram(
                "waffle_dispatch_phase_seconds",
                phase=phase, kernel=rec.kernel, op=rec.op,
                k=str(rec.k), geom=rec.geom,
            ).observe(seconds)
    except Exception:  # noqa: BLE001 - pure observability
        pass


def _publish_histograms(rec: DispatchRecord,
                        phases: Dict[str, float]) -> None:
    try:
        from waffle_con_tpu.obs import metrics as obs_metrics

        if not obs_metrics.metrics_enabled():
            return
        reg = obs_metrics.registry()
        for phase, seconds in phases.items():
            reg.histogram(
                "waffle_dispatch_phase_seconds",
                phase=phase, kernel=rec.kernel, op=rec.op,
                k=str(rec.k), geom=rec.geom,
            ).observe(seconds)
    except Exception:  # noqa: BLE001 - pure observability
        pass


# -- reads ------------------------------------------------------------


def totals() -> Dict[str, float]:
    """Cumulative per-phase seconds across every closed record (the
    quantity ``SearchReport`` diffs around one search)."""
    out = {p: 0.0 for p in PHASES}
    with _agg_lock:
        for slot in _agg.values():
            for p in PHASES:
                out[p] += slot[p]
    return out


def snapshot() -> Dict[str, Dict]:
    """JSON-ready per-(kernel, op, k, geom) phase summary, the form
    bench evidence embeds: ``{label: {phase: s, count, wall_s,
    mean_ms}}``, labels like ``solo/run/k4/B4R256W64``."""
    with _agg_lock:
        items = [(k, dict(v)) for k, v in _agg.items()]
    out: Dict[str, Dict] = {}
    for (kernel, op, k, geom), slot in sorted(items):
        label = f"{kernel}/{op}/k{k}" + (f"/{geom}" if geom else "")
        count = int(slot["count"])
        out[label] = {
            **{p: round(slot[p], 6) for p in PHASES},
            "count": count,
            "wall_s": round(slot["wall_s"], 6),
            "mean_ms": round(
                slot["wall_s"] / count * 1e3, 3
            ) if count else 0.0,
        }
    return out


def recent_records(limit: Optional[int] = None) -> List[DispatchRecord]:
    """The most recently closed records, oldest first (conservation
    test surface)."""
    with _agg_lock:
        snap = list(_recent)
    return snap[-limit:] if limit is not None else snap


def reset() -> None:
    """Drop aggregates and the recent ring (tests / bench warmup)."""
    with _agg_lock:
        _agg.clear()
        _recent.clear()
    _ACTIVE.record = None
