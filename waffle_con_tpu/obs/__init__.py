"""Observability subsystem: tracing, metrics, and search reports.

Three cooperating pieces, all **off by default** and near-zero-cost when
disabled:

* :mod:`~waffle_con_tpu.obs.trace` — span-based host tracer
  (search -> queue-pop batch -> dispatch -> device-sync) exporting
  Chrome trace-event JSON (Perfetto-viewable), with an optional
  ``jax.profiler.TraceAnnotation`` bridge.  Enable: ``WAFFLE_TRACE=1``
  (or ``=<path>`` to auto-write at exit), or programmatically.
* :mod:`~waffle_con_tpu.obs.metrics` — process-wide registry of
  counters, gauges, and histograms (per-backend dispatch latency, queue
  depth, branches-per-dispatch, handle-arena occupancy, supervisor
  retry/demotion counts) with JSON and Prometheus-text exposition.
  Enable: ``WAFFLE_METRICS=1`` or programmatically.
* :mod:`~waffle_con_tpu.obs.report` — :class:`SearchReport`, the
  structured per-search summary every engine stores as
  ``last_search_report`` and ``bench.py`` embeds in evidence JSON.
* :mod:`~waffle_con_tpu.obs.phases` — phase-attributed dispatch
  profiling (``WAFFLE_PROFILE=1``): every dispatch split into
  host-prep / device-compute / transfer / host-post, labeled by kernel
  family, speculative K, and geometry bucket; rolled into
  ``SearchReport.extra`` and the ``bench.py`` evidence ``phases``
  summary.
* :mod:`~waffle_con_tpu.obs.perfdb` — append-only JSONL performance
  history (``evidence/perfdb.jsonl`` / ``WAFFLE_PERFDB``); every bench
  and CI run appends a schema-versioned record, ``scripts/
  perf_report.py`` renders the trend, and the CI steps/s gate reads
  its rolling baseline from it.

Two **always-on** pieces ride alongside (both lock-cheap by design;
the hot-loop 620 steps/s floor gates their overhead):

* :mod:`~waffle_con_tpu.obs.flight` — bounded flight-recorder ring of
  recent serve/search records that dumps a self-contained JSON incident
  (``WAFFLE_FLIGHT_DIR``) when an anomaly trigger fires (deadline
  exceeded, backend demotion, cache quarantine, service overload,
  watchdog budget breach, slow search) — post-hoc debuggability without
  pre-enabled tracing.
* :mod:`~waffle_con_tpu.obs.slo` — rolling p50/p95/p99 + EWMA windows
  over dispatch and job/search latency, re-published into the metrics
  exposition via a registry collector, and the source of the
  ``slow_search`` trigger (current search > k x rolling p95).

Per-job tracing: the serve layer gives every job a
:class:`~waffle_con_tpu.obs.trace.TraceContext` (own Chrome pid, span
parent linkage across the worker->dispatcher thread hop, flow-event
stitching), so a multi-tenant trace export shows one connected span
tree per job.

The runtime event log (:mod:`waffle_con_tpu.runtime.events`) is one
sink of this pipeline: every recorded event also bumps the
``waffle_runtime_events_total`` counter when metrics are on (and
``waffle_runtime_events_dropped_total`` when the log saturates).
"""

from waffle_con_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_COUNT_BUCKETS,
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    enable_metrics,
    metrics_enabled,
    registry,
    reset_metrics_enabled,
)
from waffle_con_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    TRIGGER_REASONS,
    get_recorder,
)
from waffle_con_tpu.obs.phases import (  # noqa: F401
    DispatchRecord,
    enable_profiling,
    profiling_enabled,
    reset_profiling_enabled,
)
from waffle_con_tpu.obs.report import SearchReport  # noqa: F401
from waffle_con_tpu.obs.slo import SloTracker  # noqa: F401
from waffle_con_tpu.obs.trace import (  # noqa: F401
    JOB_PID_BASE,
    NULL_SPAN,
    TraceContext,
    Tracer,
    current_context,
    current_trace_id,
    get_tracer,
    set_current_context,
    span,
    tracing_enabled,
)


def obs_enabled() -> bool:
    """Whether any observability pipeline is recording (the gate for
    installing dispatch instrumentation)."""
    return metrics_enabled() or tracing_enabled() or profiling_enabled()
