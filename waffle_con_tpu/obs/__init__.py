"""Observability subsystem: tracing, metrics, and search reports.

Three cooperating pieces, all **off by default** and near-zero-cost when
disabled:

* :mod:`~waffle_con_tpu.obs.trace` — span-based host tracer
  (search -> queue-pop batch -> dispatch -> device-sync) exporting
  Chrome trace-event JSON (Perfetto-viewable), with an optional
  ``jax.profiler.TraceAnnotation`` bridge.  Enable: ``WAFFLE_TRACE=1``
  (or ``=<path>`` to auto-write at exit), or programmatically.
* :mod:`~waffle_con_tpu.obs.metrics` — process-wide registry of
  counters, gauges, and histograms (per-backend dispatch latency, queue
  depth, branches-per-dispatch, handle-arena occupancy, supervisor
  retry/demotion counts) with JSON and Prometheus-text exposition.
  Enable: ``WAFFLE_METRICS=1`` or programmatically.
* :mod:`~waffle_con_tpu.obs.report` — :class:`SearchReport`, the
  structured per-search summary every engine stores as
  ``last_search_report`` and ``bench.py`` embeds in evidence JSON.

The runtime event log (:mod:`waffle_con_tpu.runtime.events`) is one
sink of this pipeline: every recorded event also bumps the
``waffle_runtime_events_total`` counter when metrics are on.
"""

from waffle_con_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_COUNT_BUCKETS,
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    enable_metrics,
    metrics_enabled,
    registry,
    reset_metrics_enabled,
)
from waffle_con_tpu.obs.report import SearchReport  # noqa: F401
from waffle_con_tpu.obs.trace import (  # noqa: F401
    NULL_SPAN,
    Tracer,
    get_tracer,
    span,
    tracing_enabled,
)


def obs_enabled() -> bool:
    """Whether any observability pipeline is recording (the gate for
    installing dispatch instrumentation)."""
    return metrics_enabled() or tracing_enabled()
