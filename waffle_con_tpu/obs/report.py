"""Structured per-search report returned alongside consensus results.

Replaces the engines' end-of-search ``logger.debug`` triples
(``nodes_explored`` / ``nodes_ignored`` / ``peak_queue_size``) with one
structured object: engines store it as ``engine.last_search_report``
(and keep the dict-shaped ``last_search_stats`` for backward
compatibility), ``bench.py`` embeds it per timed iteration in the
evidence JSON, and a single one-line summary is logged — at INFO when
``config.log_search_summary`` is set, else at DEBUG.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional

from waffle_con_tpu.ops.scorer import DISPATCH_COUNTER_KEYS

logger = logging.getLogger(__name__)


def _dispatch_total(counters: Dict[str, int]) -> int:
    # same quantity as runtime.watchdog.dispatch_total (imported lazily
    # there to keep obs a leaf package, cycle-free)
    return sum(int(counters.get(k, 0)) for k in DISPATCH_COUNTER_KEYS)


class SearchReport:
    """Search-shape and time accounting for one ``consensus()`` call."""

    __slots__ = (
        "engine", "backend", "wall_s", "nodes_explored", "nodes_ignored",
        "peak_queue_size", "dispatch_counts", "dispatch_total",
        "time_breakdown", "n_results", "consensus_len", "extra",
    )

    def __init__(
        self,
        engine: str,
        backend: str,
        wall_s: float,
        nodes_explored: int,
        nodes_ignored: int,
        peak_queue_size: int,
        dispatch_counts: Dict[str, int],
        time_breakdown: Optional[Dict[str, float]] = None,
        n_results: int = 0,
        consensus_len: int = 0,
        extra: Optional[Dict] = None,
    ) -> None:
        self.engine = engine
        self.backend = backend
        self.wall_s = float(wall_s)
        self.nodes_explored = int(nodes_explored)
        self.nodes_ignored = int(nodes_ignored)
        self.peak_queue_size = int(peak_queue_size)
        self.dispatch_counts = dict(dispatch_counts)
        self.dispatch_total = _dispatch_total(self.dispatch_counts)
        self.time_breakdown = dict(time_breakdown or {})
        self.n_results = int(n_results)
        self.consensus_len = int(consensus_len)
        self.extra = dict(extra or {})

    def to_dict(self) -> Dict:
        out = {
            "engine": self.engine,
            "backend": self.backend,
            "wall_s": round(self.wall_s, 6),
            "nodes_explored": self.nodes_explored,
            "nodes_ignored": self.nodes_ignored,
            "peak_queue_size": self.peak_queue_size,
            "dispatch_total": self.dispatch_total,
            "dispatch_counts": dict(self.dispatch_counts),
            "n_results": self.n_results,
            "consensus_len": self.consensus_len,
        }
        if self.time_breakdown:
            out["time_breakdown"] = {
                k: round(v, 6) for k, v in sorted(self.time_breakdown.items())
            }
        if self.extra:
            out["extra"] = dict(self.extra)
        return out

    def summary_line(self) -> str:
        """The single one-line search summary (log surface; tests format
        it, so keep it %-free and stable-prefixed)."""
        return (
            f"search summary: engine={self.engine} backend={self.backend} "
            f"nodes_explored={self.nodes_explored} "
            f"nodes_ignored={self.nodes_ignored} "
            f"peak_queue={self.peak_queue_size} "
            f"dispatches={self.dispatch_total} "
            f"results={self.n_results} wall_s={self.wall_s:.4f}"
        )

    def __repr__(self) -> str:
        return f"SearchReport({self.to_dict()!r})"


def run_reported_search(engine, engine_label: str, impl: Callable):
    """Run one engine search under a ``search`` tracer span and publish
    its :class:`SearchReport`.

    The engines' public ``consensus()`` methods are thin wrappers over
    this: ``impl`` is the renamed search body, which must leave
    ``engine.last_search_stats`` populated (``nodes_explored`` /
    ``nodes_ignored`` / ``peak_queue_size`` / ``scorer_counters`` and,
    when known, ``backend``).  On return the report is stored as
    ``engine.last_search_report`` and its one-line summary is logged —
    at INFO when ``config.log_search_summary`` is set, else at DEBUG.
    """
    # lazy submodule imports keep obs.report importable mid-package-init
    from waffle_con_tpu.obs import audit as obs_audit
    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.obs import metrics as obs_metrics
    from waffle_con_tpu.obs import phases as obs_phases
    from waffle_con_tpu.obs import slo as obs_slo
    from waffle_con_tpu.obs import trace as obs_trace

    tracer = obs_trace.get_tracer()
    totals_before = tracer.category_totals() if tracer.enabled else None
    phases_before = (
        obs_phases.totals() if obs_phases.profiling_enabled() else None
    )
    #: lockstep shadow execution (WAFFLE_SHADOW=python, debug tool —
    #: never enabled in serve paths): the python-oracle twin runs in
    #: step with this search and per-pop decisions are compared
    shadow = obs_audit.maybe_shadow(engine, engine_label)
    t0 = time.perf_counter()
    with tracer.span("search", "search", engine=engine_label):
        results = impl() if shadow is None else shadow.run(impl)
    wall_s = time.perf_counter() - t0

    stats = getattr(engine, "last_search_stats", None) or {}
    breakdown: Dict[str, float] = {}
    if totals_before is not None:
        for cat, total in tracer.category_totals().items():
            if cat == "search":
                continue
            delta = total - totals_before.get(cat, 0.0)
            if delta > 0.0:
                breakdown[cat] = delta

    n_results, consensus_len = _result_shape(results)
    report = SearchReport(
        engine=engine_label,
        backend=stats.get("backend")
        or getattr(engine.config, "backend", "unknown"),
        wall_s=wall_s,
        nodes_explored=stats.get("nodes_explored", 0),
        nodes_ignored=stats.get("nodes_ignored", 0),
        peak_queue_size=stats.get("peak_queue_size", 0),
        dispatch_counts=stats.get("scorer_counters", {}),
        time_breakdown=breakdown,
        n_results=n_results,
        consensus_len=consensus_len,
    )
    trace_id = obs_trace.current_trace_id()
    if trace_id is not None:
        report.extra["trace_id"] = trace_id
    if phases_before is not None:
        # per-phase dispatch time spent DURING this search (process-
        # wide totals diffed around it, same shape as time_breakdown)
        deltas = {
            p: round(total - phases_before.get(p, 0.0), 6)
            for p, total in obs_phases.totals().items()
        }
        if any(v > 0.0 for v in deltas.values()):
            report.extra["phases"] = deltas
    # rolling-SLO check BEFORE this sample joins the window (a
    # pathological search must not dilute the baseline it is judged
    # against); fires the flight recorder's slow_search trigger
    if obs_slo.observe_search(wall_s, trace_id=trace_id):
        report.extra["slow_search"] = True
    obs_flight.record(
        "search", trace_id=trace_id, engine=engine_label,
        backend=report.backend, wall_s=round(wall_s, 6),
        dispatches=report.dispatch_total,
        nodes=report.nodes_explored,
    )
    engine.last_search_report = report

    if obs_metrics.metrics_enabled():
        reg = obs_metrics.registry()
        reg.counter("waffle_searches_total", engine=engine_label).inc()
        reg.gauge(
            "waffle_search_peak_queue_depth", engine=engine_label
        ).set(report.peak_queue_size)

    level = (
        logging.INFO
        if getattr(engine.config, "log_search_summary", False)
        else logging.DEBUG
    )
    if logger.isEnabledFor(level):
        logger.log(level, "%s", report.summary_line())
    return results


def _result_shape(results) -> "tuple[int, int]":
    """(result count, best consensus length) across the engines' three
    return shapes: ``[Consensus]``, ``[DualConsensus]``, and the
    priority engine's ``PriorityConsensus``."""
    try:
        if results is None:
            return 0, 0
        seq = getattr(results, "consensuses", results)
        n = len(seq)
        if n == 0:
            return 0, 0
        first = seq[0]
        if hasattr(first, "sequence"):
            return n, len(first.sequence)
        inner = getattr(first, "consensus1", None)
        if inner is not None:
            return n, len(inner.sequence)
        return n, 0
    except Exception:  # observability must never break the search
        return 0, 0
