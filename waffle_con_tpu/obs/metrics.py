"""Process-wide metrics registry: counters, gauges, histograms.

The consensus runtime's hot loop is one blocking scorer dispatch per
candidate extension; on tunneled device platforms wall time is
``dispatches x latency``, so the registry's first-class citizens are the
per-backend dispatch wall-clock latency **histograms** (recorded by
:class:`~waffle_con_tpu.obs.instrument.TimedScorer`), alongside queue
depth, branches-per-dispatch, handle-arena occupancy, and the
retry/demotion counters fed from the PR-1 supervisor via
:mod:`waffle_con_tpu.runtime.events` (the event log is one sink of this
pipeline, the registry is another).

Exposition: :meth:`MetricsRegistry.snapshot` (JSON-ready dict, embedded
in ``bench.py`` evidence) and :meth:`MetricsRegistry.render_prometheus`
(Prometheus text format 0.0.4).

Overhead contract: everything here is **off by default**.  Callers gate
instrumentation on :func:`metrics_enabled` (``WAFFLE_METRICS=1`` or
:func:`enable_metrics`); with metrics off, no instrument objects are
created and the engines' per-search cost is a handful of boolean checks.
"""

from __future__ import annotations

import bisect
import re
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec

#: default latency buckets (seconds): spans the observed dispatch range
#: from sub-100us fused XLA:CPU calls to multi-second tunneled TPU
#: round-trips, roughly x2.5 per step like Prometheus' defaults
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: default buckets for small-count histograms (branches per dispatch)
DEFAULT_COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class Counter:
    """Monotonically increasing counter."""

    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = lockcheck.make_lock("obs.metrics.Counter")

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def read(self) -> float:
        with self._lock:
            return self.value


class Gauge:
    """Point-in-time value (queue depth, arena occupancy)."""

    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = lockcheck.make_lock("obs.metrics.Gauge")

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def read(self) -> float:
        with self._lock:
            return self.value


class Histogram:
    """Fixed-bucket histogram with Prometheus semantics.

    ``bounds`` are the inclusive upper edges of the finite buckets; one
    implicit ``+Inf`` bucket catches the overflow.  ``counts[i]`` is the
    NON-cumulative count of observations with
    ``bounds[i-1] < v <= bounds[i]`` (Prometheus exposition cumulates at
    render time).
    """

    __slots__ = ("bounds", "counts", "sum", "count", "_lock")

    def __init__(self, bounds: Iterable[float]) -> None:
        self.bounds = tuple(sorted(float(b) for b in bounds))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = lockcheck.make_lock("obs.metrics.Histogram")

    def _bucket_index(self, value: float) -> int:
        # bisect_left matches the inclusive-upper-edge contract
        # (value == bounds[i] lands in bucket i); NaN compares False
        # against everything, which bisect would place at index 0 —
        # route it to the +Inf overflow bucket like the scan it replaced
        if value != value:
            return len(self.bounds)
        return bisect.bisect_left(self.bounds, value)

    def observe(self, value: float) -> None:
        i = self._bucket_index(value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def read(self) -> Tuple[list, float, int]:
        """Consistent ``(counts, sum, count)`` triple taken under the
        instrument lock — exposition must not see a half-applied
        ``observe`` from a concurrently recording thread (the serve
        layer records from many workers at once)."""
        with self._lock:
            return list(self.counts), self.sum, self.count

    def cumulative(self) -> list:
        """Cumulative counts per bound (Prometheus ``le`` semantics),
        with the ``+Inf`` total last."""
        counts, _sum, _count = self.read()
        out = []
        running = 0
        for c in counts:
            running += c
            out.append(running)
        return out


_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _format_labels(key: _LabelKey, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def _parse_labels(label_str: str) -> Dict[str, str]:
    """Inverse of :func:`_format_labels` for the label values this
    registry actually emits (identifiers, backend names, service names —
    never embedded quotes)."""
    return dict(_LABEL_RE.findall(label_str or ""))


class MetricsRegistry:
    """Thread-safe named-metric store with labelled children.

    One metric name maps to a family; each distinct label set is its own
    child instrument.  Families are type-stable: registering the same
    name as a different type raises.
    """

    def __init__(self) -> None:
        self._lock = lockcheck.make_lock("obs.metrics.MetricsRegistry")
        #: name -> (kind, {label_key: instrument}, histogram bounds)
        self._families: Dict[str, Tuple[str, Dict[_LabelKey, object], Optional[tuple]]] = {}
        #: exposition-time callbacks (e.g. the SLO tracker re-publishing
        #: rolling percentiles as gauges); run before every snapshot
        self._collectors: List[Callable[[], None]] = []

    def register_collector(self, fn: Callable[[], None]) -> None:
        """Register a callback invoked at the start of every
        :meth:`snapshot` / :meth:`render_prometheus` so derived metrics
        (rolling percentiles) are fresh at read time."""
        with self._lock:
            self._collectors.append(fn)

    def _collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # a broken collector must not kill reads
                pass

    def _child(self, kind: str, name: str, labels: Dict[str, str],
               bounds: Optional[Iterable[float]] = None):
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (kind, {}, tuple(bounds) if bounds else None)
                self._families[name] = fam
            elif fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}"
                )
            child = fam[1].get(key)
            if child is None:
                if kind == "counter":
                    child = Counter()
                elif kind == "gauge":
                    child = Gauge()
                else:
                    child = Histogram(fam[2] or DEFAULT_LATENCY_BUCKETS)
                fam[1][key] = child
            return child

    def counter(self, name: str, **labels) -> Counter:
        return self._child("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._child("gauge", name, labels)

    def histogram(
        self, name: str, buckets: Optional[Iterable[float]] = None, **labels
    ) -> Histogram:
        return self._child("histogram", name, labels, bounds=buckets)

    def reset(self) -> None:
        with self._lock:
            self._families.clear()

    # -- federation ----------------------------------------------------

    def merge_snapshot(self, snap: Dict, **extra_labels) -> int:
        """Re-ingest another process's :meth:`snapshot` under added
        labels — the proc front door merges each worker's periodic
        STATS snapshot with ``worker=<name>`` so one exposition covers
        the fleet.

        Remote snapshots are cumulative, so children are **set** to the
        shipped values (last-write-wins per worker), not incremented.
        Malformed or type-colliding families are skipped, never raised:
        a worker snapshot must not be able to kill the door's read
        loop.  Returns the number of series merged.
        """
        if not isinstance(snap, dict):
            return 0
        merged = 0
        for name, family in snap.items():
            if not isinstance(family, dict):
                continue
            kind = family.get("type")
            series = family.get("series")
            if kind not in ("counter", "gauge", "histogram") \
                    or not isinstance(series, dict):
                continue
            for label_str, value in series.items():
                labels = _parse_labels(str(label_str))
                labels.update(extra_labels)
                try:
                    if kind == "histogram":
                        buckets = value.get("buckets", {})
                        ordered = sorted(
                            ((float(b), int(c))
                             for b, c in buckets.items()),
                        )
                        if not ordered:
                            continue
                        child = self._child(
                            "histogram", name, labels,
                            bounds=[b for b, _c in ordered],
                        )
                        with child._lock:
                            child.counts = (
                                [c for _b, c in ordered]
                                + [int(value.get("overflow", 0))]
                            )
                            child.sum = float(value.get("sum", 0.0))
                            child.count = int(value.get("count", 0))
                    else:
                        child = self._child(kind, name, labels)
                        with child._lock:
                            child.value = float(value)
                    merged += 1
                except (ValueError, TypeError, AttributeError):
                    continue
        return merged

    # -- exposition ----------------------------------------------------

    def snapshot(self) -> Dict:
        """JSON-ready dump: ``{name: {"type": ..., "series": {labelstr:
        value-or-histogram-dict}}}`` (the form ``bench.py`` embeds)."""
        self._collect()
        with self._lock:
            families = {
                name: (kind, dict(children))
                for name, (kind, children, _b) in self._families.items()
            }
        out: Dict[str, Dict] = {}
        for name, (kind, children) in sorted(families.items()):
            series = {}
            for key, child in sorted(children.items()):
                label_str = _format_labels(key) or "{}"
                if kind == "histogram":
                    counts, h_sum, h_count = child.read()
                    series[label_str] = {
                        "buckets": {
                            str(b): c
                            for b, c in zip(child.bounds, counts)
                        },
                        "overflow": counts[-1],
                        "sum": h_sum,
                        "count": h_count,
                    }
                else:
                    series[label_str] = child.read()
            out[name] = {"type": kind, "series": series}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self._collect()
        with self._lock:
            families = {
                name: (kind, dict(children))
                for name, (kind, children, _b) in self._families.items()
            }
        lines = []
        for name, (kind, children) in sorted(families.items()):
            lines.append(f"# TYPE {name} {kind}")
            for key, child in sorted(children.items()):
                if kind == "histogram":
                    counts, h_sum, h_count = child.read()
                    cumulative, running = [], 0
                    for c in counts:
                        running += c
                        cumulative.append(running)
                    for b, c in zip(child.bounds, cumulative):
                        le = _format_labels(key, f'le="{b}"')
                        lines.append(f"{name}_bucket{le} {c}")
                    le = _format_labels(key, 'le="+Inf"')
                    lines.append(f"{name}_bucket{le} {cumulative[-1]}")
                    lines.append(
                        f"{name}_sum{_format_labels(key)} {h_sum}"
                    )
                    lines.append(
                        f"{name}_count{_format_labels(key)} {h_count}"
                    )
                else:
                    lines.append(
                        f"{name}{_format_labels(key)} {child.read()}"
                    )
        return "\n".join(lines) + "\n"


#: the process-wide registry every component records into
_REGISTRY = MetricsRegistry()
#: programmatic override; None defers to the WAFFLE_METRICS env var
_FORCED: Optional[bool] = None


def registry() -> MetricsRegistry:
    return _REGISTRY


def metrics_enabled() -> bool:
    """Whether instrumentation should record (``WAFFLE_METRICS`` env, or
    a programmatic :func:`enable_metrics` override)."""
    if _FORCED is not None:
        return _FORCED
    return envspec.flag("WAFFLE_METRICS")


def enable_metrics(on: bool = True) -> None:
    """Programmatic enable/disable (overrides the env var)."""
    global _FORCED
    _FORCED = bool(on)


def reset_metrics_enabled() -> None:
    """Drop the programmatic override; the env var rules again."""
    global _FORCED
    _FORCED = None
