"""waffle_con_tpu — a TPU-native dynamic-WFA consensus framework.

A ground-up rebuild of the capabilities of ``waffle_con``
(PacificBiosciences, reference at ``/root/reference``): backbone-free
consensus generation over sets of noisy long reads via a least-cost-first
search whose per-read scoring step is an incremental edit-distance
wavefront (dynamic WFA).

Architecture (TPU-first, not a translation):

* ``ops``      — the alignment kernels.  A pure-Python incremental DWFA
  (:class:`~waffle_con_tpu.ops.dwfa.DWFALite`, parity oracle), a one-shot
  WFA edit distance, and a batched JAX scorer that keeps every read's
  wavefront as one ``[branch, read, 2*E+1]`` device array and advances all
  of them in a single fused XLA step per consensus symbol.
* ``models``   — the consensus engines (single, dual/diplotype,
  priority-chain, multi).  Host-side Dijkstra-like search (priority queue,
  candidate nomination, thresholds, offset activation) over an abstract
  branch store so CPU and TPU scorers are interchangeable.
* ``parallel`` — ``jax.sharding`` mesh utilities: reads sharded across
  chips, candidate-vote histograms reduced with ``psum`` over ICI.
* ``utils``    — configuration, priority-queue tracker, synthetic data
  generation, golden-fixture loaders.
* ``native``   — C++ implementations of the kernels and engines (the fast
  CPU path and the benchmark baseline), bound via ctypes.
* ``runtime``  — fault tolerance: the ``BackendSupervisor`` scorer proxy
  (retry/backoff, mid-search backend demotion), deterministic fault
  injection, dispatch-budget + deadline watchdog, process-wide event log.
* ``obs``      — observability: span tracer (Chrome trace export), metrics
  registry (Prometheus/JSON exposition), ``TimedScorer`` dispatch-latency
  proxy, structured per-search reports.
* ``serve``    — multi-tenant serving: ``ConsensusService`` worker pool
  with a bounded reject-on-full admission queue, per-job deadlines /
  cancellation / priorities, and cross-job dynamic batching of scorer
  dispatches (``BatchingDispatcher`` + ``CoalescingScorer``) so N
  concurrent jobs amortize device dispatch overhead while staying
  byte-identical to serial runs.

Reference layer map: see SURVEY.md §1; the public API parity targets the
reference's six modules (``/root/reference/src/lib.rs:38-55``).
"""

from waffle_con_tpu.config import CdwfaConfig, CdwfaConfigBuilder, ConsensusCost
from waffle_con_tpu.models.consensus import Consensus, ConsensusDWFA
from waffle_con_tpu.models.dual_consensus import DualConsensus, DualConsensusDWFA
from waffle_con_tpu.models.multi_consensus import MultiConsensus
from waffle_con_tpu.models.priority_consensus import (
    PriorityConsensus,
    PriorityConsensusDWFA,
)
from waffle_con_tpu.serve import (
    ConsensusService,
    JobRequest,
    ServeConfig,
    ServiceOverloaded,
)

__version__ = "0.1.0"

__all__ = [
    "CdwfaConfig",
    "CdwfaConfigBuilder",
    "ConsensusCost",
    "Consensus",
    "ConsensusDWFA",
    "ConsensusService",
    "DualConsensus",
    "DualConsensusDWFA",
    "JobRequest",
    "MultiConsensus",
    "PriorityConsensus",
    "PriorityConsensusDWFA",
    "ServeConfig",
    "ServiceOverloaded",
]
