"""Seeded synthetic consensus datasets for tests and benchmarks.

Capability parity with ``/root/reference/src/example_gen.rs:11-64``: a
random consensus over a small alphabet plus ``num_samples`` noisy copies
with per-base error ``error_rate`` split evenly between substitution,
deletion and insertion.  Deterministic for a given seed (numpy PCG64; the
reference's ChaCha12 stream is not reproduced bit-for-bit — datasets are
regenerated, not ported, per SURVEY.md §7 step 1).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def corrupt(
    consensus: bytes,
    error_rate: float,
    rng: np.random.Generator,
    alphabet_size: int = 4,
) -> bytes:
    """One noisy copy of ``consensus``: per-base error split evenly between
    substitution, deletion and insertion (reference error model,
    ``/root/reference/src/example_gen.rs:30-58``)."""
    seq_len = len(consensus)
    seq = bytearray()
    con_index = 0
    while con_index < seq_len:
        c = int(consensus[con_index])
        if rng.random() < error_rate:
            error_type = int(rng.integers(0, 3))
            if error_type == 0:
                # substitution: any *other* symbol
                sub_offset = int(rng.integers(0, alphabet_size - 1))
                seq.append((c + 1 + sub_offset) % alphabet_size)
                con_index += 1
            elif error_type == 1:
                # deletion
                con_index += 1
            else:
                # insertion (consensus position is retried)
                seq.append(int(rng.integers(0, alphabet_size)))
        else:
            seq.append(c)
            con_index += 1
    return bytes(seq)


def generate_test(
    alphabet_size: int,
    seq_len: int,
    num_samples: int,
    error_rate: float,
    seed: int = 0,
) -> Tuple[bytes, List[bytes]]:
    """Return ``(consensus, samples)`` with symbols in ``0..alphabet_size``."""
    assert alphabet_size > 1
    assert 0.0 <= error_rate <= 1.0

    rng = np.random.default_rng(seed)
    consensus = rng.integers(0, alphabet_size, size=seq_len, dtype=np.uint8)
    samples = [
        corrupt(bytes(consensus), error_rate, rng, alphabet_size)
        for _ in range(num_samples)
    ]
    return bytes(consensus), samples
