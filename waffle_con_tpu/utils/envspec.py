"""Central registry of every ``WAFFLE_*`` environment knob.

Every env read in the package goes through this module (machine-enforced
by lint rule **WL001** in :mod:`waffle_con_tpu.analysis.lint`): a knob
must be declared here — name, type, default, one-line doc — before any
code may read it, and the declared set is doc-synced against the README
reference table (``scripts/waffle_lint.py --env-table`` emits the
table).  That kills the two historical failure modes: knobs read but
never documented, and knobs documented but no longer read.

The getters deliberately mirror ``os.environ.get`` semantics so call
sites migrate without behavior change:

* :func:`get_raw` — exact ``os.environ.get(name, default)`` passthrough
  (callers keep their local parsing quirks: tri-states, false-sets,
  save/restore round-trips).
* :func:`flag` — the package's ``not in ("", "0")`` enablement idiom
  (metrics/trace/profile/lockcheck family).
* :func:`get_int` / :func:`get_float` — numeric with optional clamping;
  unset or garbage falls back to the default (never raises).
* :func:`is_set` — presence test.

Reading an *unregistered* name raises ``KeyError`` at call time, so a
new knob cannot ship without its registry row (and therefore without
README documentation).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = [
    "EnvKnob", "KNOBS", "knobs", "get_raw", "flag", "get_int",
    "get_float", "is_set", "env_table_markdown",
]


@dataclass(frozen=True)
class EnvKnob:
    """One registered environment knob."""

    name: str      # full env-var name, WAFFLE_*
    kind: str      # "flag" | "int" | "float" | "str" | "path" | "enum"
    default: str   # human-readable default (display only, not parsed)
    doc: str       # one-line description for the README table


def _k(name: str, kind: str, default: str, doc: str) -> Tuple[str, EnvKnob]:
    return name, EnvKnob(name, kind, default, doc)


#: the authoritative knob registry, grouped roughly by subsystem.  The
#: README env-reference table is generated from this dict — edit here,
#: then re-run ``python scripts/waffle_lint.py --env-table``.
KNOBS: Dict[str, EnvKnob] = dict((
    # -- ragged cross-job dispatch (ops/ragged.py) ---------------------
    _k("WAFFLE_RAGGED", "flag", "1 (on)",
       "Ragged dispatch master switch; `0`/`false`/`off`/`no` disable"),
    _k("WAFFLE_RAGGED_ROWS", "int", "256",
       "Band-arena pool rows (reads across all jobs), clamped 16..65536"),
    _k("WAFFLE_RAGGED_PAGE", "int", "8",
       "Arena rows per page (residency quantum), clamped 1..256"),
    _k("WAFFLE_RAGGED_E", "int", "32",
       "Arena pool band half-width E (W = 2E + 2), clamped 8..512"),
    _k("WAFFLE_RAGGED_L", "int", "512",
       "Arena staged read columns, clamped 64..32768"),
    _k("WAFFLE_RAGGED_C", "int", "2048",
       "Arena per-member consensus capacity, clamped 256..65536"),
    _k("WAFFLE_RAGGED_GANG", "int", "8",
       "Max members per ragged kernel call, clamped 2..64"),
    _k("WAFFLE_RAGGED_MIXED_W", "flag", "1 (on)",
       "Width-agnostic arena pages: gang members of different band "
       "widths (per-row W stride); `0` restores the W-equality gate"),
    # -- kernel selection (ops/) ---------------------------------------
    _k("WAFFLE_PALLAS", "enum", "auto",
       "Pallas kernel mode: `auto` (on iff TPU), `1` (interpret on "
       "CPU), `interpret`, `0` (off)"),
    _k("WAFFLE_PALLAS_I16", "flag", "1 (on)",
       "int16 DP tiles in the Pallas run kernels; `0` forces int32"),
    _k("WAFFLE_XLA_I16", "enum", "unset (auto)",
       "int16 band-state for XLA run kernels: `1` force on, `0` force "
       "off, unset = TPU only"),
    _k("WAFFLE_RUN_COLS", "int", "unset (per-backend, 4)",
       "Speculative columns K per device loop iteration, clamped "
       "1..64; read per dispatch"),
    _k("WAFFLE_MEGASTEP", "flag", "1 (on)",
       "Device-resident megastep runs: the engines' pop loop engages "
       "`run_mega` (M blocks of K columns per while-loop iteration, "
       "one bundled result transfer); `0` restores plain `run_extend` "
       "stepping"),
    _k("WAFFLE_MEGA_SYMS", "int", "65536",
       "Per-dispatch commit budget of a megastep run (caps max_steps; "
       "a capped run stops with code 4 and the engine re-engages), "
       "clamped 1..1048576"),
    _k("WAFFLE_MEGA_BLOCKS", "int", "8",
       "Megastep blocks M per while-loop iteration (each block is K "
       "masked columns; traced once, so compile cost stays at the K=1 "
       "body), clamped 1..64"),
    # -- search / frontier speculation ---------------------------------
    _k("WAFFLE_FRONTIER_M", "int", "unset (adaptive)",
       "Explicit frontier-gang width M; `0`/`1` disable speculation"),
    _k("WAFFLE_FRONTIER_SAMPLE", "int", "64",
       "Frontier sampler pop decimation (one record per N pops); `0` "
       "disables"),
    # -- serve placement (serve/placement.py) --------------------------
    _k("WAFFLE_PLACEMENT_LEARNED", "flag", "0 (off)",
       "Learn mesh-vs-arena placement from perfdb substrate profiles "
       "(rolling per-geometry medians); cold history falls back to the "
       "static read-count threshold"),
    # -- runtime supervision -------------------------------------------
    _k("WAFFLE_WATCHDOG", "enum", "unset (warn)",
       "`strict` turns dispatch-budget overruns into WatchdogError"),
    _k("WAFFLE_FAULTS", "str", "unset",
       "Fault-injection plan: `kind[:backend[:op[:at[:count]]]],...`"),
    _k("WAFFLE_ASYNC_SYNC", "flag", "1 (on)",
       "Deferred device-stats sync; `0` restores eager per-dispatch "
       "fetch"),
    _k("WAFFLE_LOCKCHECK", "flag", "0 (off)",
       "Runtime lock-order checker: instrumented locks record "
       "acquisition edges and raise on a cyclic (inversion) order"),
    # -- observability -------------------------------------------------
    _k("WAFFLE_METRICS", "flag", "0 (off)",
       "Metrics registry recording (counters/gauges/histograms)"),
    _k("WAFFLE_TRACE", "str", "unset (off)",
       "Host tracing: `1` in memory, a path auto-writes Chrome trace "
       "at exit"),
    _k("WAFFLE_TRACE_JAX", "flag", "0 (off)",
       "Bridge host spans into jax.profiler trace annotations"),
    _k("WAFFLE_PROFILE", "flag", "0 (off)",
       "Per-dispatch phase breakdown profiling"),
    _k("WAFFLE_FLIGHT_RING", "int", "2048",
       "Flight-recorder ring capacity in records (min 16)"),
    _k("WAFFLE_FLIGHT_DEDUPE_S", "float", "300",
       "Incident (reason, trace) dedupe window in seconds; `0` "
       "disables dedupe"),
    _k("WAFFLE_FLIGHT_DIR", "path", "unset (in-memory only)",
       "Directory receiving `incident-<seq>-<reason>.json` dumps"),
    _k("WAFFLE_SLO_WINDOW_S", "float", "300",
       "SLO rolling-window age bound in seconds"),
    _k("WAFFLE_SLO_K", "float", "3.0",
       "Slow-search threshold: k x rolling p95"),
    _k("WAFFLE_STATS_FILE", "path", "unset (off)",
       "Serving stats snapshot file, atomically rewritten each refresh"),
    _k("WAFFLE_AUDIT", "flag", "0 (off)",
       "Search decision audit log: engines emit one record per pop "
       "boundary (zero-overhead no-op when unset)"),
    _k("WAFFLE_AUDIT_DIR", "path", "unset (in-memory ring only)",
       "Directory receiving `audit-<n>-<engine>.jsonl` streams and "
       "parity dump-on-fail bundles"),
    _k("WAFFLE_AUDIT_RING", "int", "4096",
       "Per-search audit record ring capacity"),
    _k("WAFFLE_SHADOW", "str", "unset (off)",
       "`python` runs the oracle engine in lockstep with the primary "
       "and aborts at the first decision divergence (debug tool — "
       "never enable in serve paths)"),
    _k("WAFFLE_PERFDB", "path", "evidence/perfdb.jsonl",
       "Performance-history database path override"),
    # -- CI / scripts (read by scripts/ci.sh and helpers) --------------
    _k("WAFFLE_PERFDB_TOLERANCE", "float", "0.05",
       "CI: allowed fractional drop vs the rolling perfdb baseline"),
    _k("WAFFLE_PERFDB_SERVE_TOLERANCE", "float", "0.15",
       "CI: wider perfdb tolerance band for serving kinds"),
    _k("WAFFLE_PERFDB_WINDOW", "int", "10",
       "CI: perfdb rolling-baseline window (records)"),
    _k("WAFFLE_MICROBENCH_FLOOR", "float", "900",
       "CI: absolute microbench steps/s backstop floor"),
    _k("WAFFLE_TIE_HEAVY_CEILING_S", "float", "120",
       "CI: tie-heavy queue benchmark wall-clock ceiling in seconds"),
    _k("WAFFLE_STORM_JOBS_FLOOR", "float", "3.0",
       "CI: storm-harness multi-replica jobs/s floor"),
    _k("WAFFLE_STORM_P95_CEIL", "float", "3.0",
       "CI: storm-harness p95 job-latency ceiling in seconds"),
    _k("WAFFLE_STORM_SPEEDUP", "float", "0.8",
       "CI: storm multi/single jobs/s sanity floor"),
    _k("WAFFLE_STORM_SHED_P95", "float", "12.0",
       "CI: p95 ceiling with one demoted (shedding) replica, seconds"),
    _k("WAFFLE_SUITE_TIMEOUT", "int", "600",
       "Sharded suite runner per-shard timeout in seconds"),
    # -- out-of-process serving (serve/procs) -------------------------
    _k("WAFFLE_PROC_FRAME_MAX", "int", "33554432",
       "Wire protocol: maximum frame payload size in bytes (32 MiB)"),
    _k("WAFFLE_PROC_PING_S", "float", "0.5",
       "Front door: worker ping interval in seconds"),
    _k("WAFFLE_PROC_LIVENESS_S", "float", "5.0",
       "Front door: seconds without any worker frame before the "
       "liveness watchdog declares the worker lost"),
    _k("WAFFLE_STORM_PROCS_SPEEDUP", "float", "0.25",
       "CI: storm-procs multi-worker/single-process jobs/s sanity "
       "floor; the default is the documented 1-core time-slicing "
       "sanity value (measured 0.34-0.42) -- raise toward 1.5 on "
       "real multi-core hosts"),
    _k("WAFFLE_CKPT_INTERVAL_S", "float", "30",
       "Serving: periodic search-checkpoint interval in seconds for "
       "jobs run under a service/worker (0 disables periodic "
       "snapshots; deadline and drain snapshots still fire)"),
    _k("WAFFLE_CKPT_MAX_BYTES", "int", "8388608",
       "Serving: checkpoints whose wire JSON exceeds this many bytes "
       "are dropped (never truncated) -- the job stays restartable "
       "from scratch (8 MiB)"),
    _k("WAFFLE_CKPT_MIGRATE", "flag", "1 (on)",
       "Front door: resume a lost worker's started jobs from their "
       "last checkpoint on another worker; 0 falls back to "
       "restart-from-scratch (restart_lost)"),
    _k("WAFFLE_PROC_STATS_S", "float", "2.0",
       "Worker: period in seconds between federated-metrics STATS "
       "frames (each ships the worker's registry snapshot to the "
       "door); only sent while metrics are enabled"),
    _k("WAFFLE_TRACE_SPAN_CAP", "int", "512",
       "Worker: max span events shipped back per RESULT/ERROR/"
       "CHECKPOINT frame (latest kept -- completion order puts "
       "enclosing spans at the tail); min 16"),
    _k("WAFFLE_PROC_INCIDENTS", "flag", "1 (on)",
       "Worker: forward every post-dedupe flight incident to the door "
       "as an INCIDENT frame (door re-ingests with worker attribution "
       "and fleet-level dedupe); `0` keeps incidents worker-local"),
    # -- consensus cache (serve/cache) --------------------------------
    _k("WAFFLE_CACHE", "flag", "unset (off)",
       "Serving: content-addressed consensus cache at admission -- "
       "exact duplicates answer from the result store, read-superset "
       "submissions resume cached checkpoints or certify cached "
       "consensuses (see serve/cache/)"),
    _k("WAFFLE_CACHE_MAX", "int", "256",
       "Consensus cache: in-memory result-store entry cap (LRU)"),
    _k("WAFFLE_CACHE_CKPTS", "int", "64",
       "Consensus cache: checkpoint-store entry cap for superset "
       "resume (LRU)"),
    _k("WAFFLE_CACHE_PROPOSALS", "flag", "1 (on)",
       "Consensus cache: certify cached near-miss consensuses with "
       "one exact scoring pass (propose-then-verify tier); 0 keeps "
       "only the exact-hit and checkpoint-superset tiers"),
    _k("WAFFLE_CACHE_DIR", "path", "unset",
       "Consensus cache: optional on-disk result store directory -- "
       "entries are sha256-sealed via MANIFEST.json; corrupt files "
       "quarantine to _quarantine/ and are never served"),
))


def knobs() -> Tuple[EnvKnob, ...]:
    """All registered knobs, in registry (subsystem-grouped) order."""
    return tuple(KNOBS.values())


def _require(name: str) -> None:
    if name not in KNOBS:
        raise KeyError(
            f"unregistered WAFFLE env knob {name!r}: declare it in "
            "waffle_con_tpu/utils/envspec.py (and the README table) "
            "before reading it"
        )


def get_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """Exact ``os.environ.get(name, default)`` for a registered knob."""
    _require(name)
    return os.environ.get(name, default)


def flag(name: str) -> bool:
    """The package's enablement idiom: set and not ``"0"``."""
    _require(name)
    return os.environ.get(name, "") not in ("", "0")


def get_int(name: str, default: int,
            lo: Optional[int] = None, hi: Optional[int] = None) -> int:
    """Integer knob with optional clamping; unset/garbage -> default."""
    _require(name)
    raw = os.environ.get(name, "")
    try:
        value = int(raw) if raw != "" else default
    except ValueError:
        return default
    if lo is not None:
        value = max(lo, value)
    if hi is not None:
        value = min(hi, value)
    return value


def get_float(name: str, default: float) -> float:
    """Float knob; unset/garbage -> default."""
    _require(name)
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw != "" else default
    except ValueError:
        return default


def is_set(name: str) -> bool:
    _require(name)
    return name in os.environ


def env_table_markdown() -> str:
    """The README env-reference table (between the envspec markers)."""
    lines = [
        "| Knob | Type | Default | Description |",
        "| --- | --- | --- | --- |",
    ]
    for knob in knobs():
        lines.append(
            f"| `{knob.name}` | {knob.kind} | {knob.default} | "
            f"{knob.doc} |"
        )
    return "\n".join(lines)
