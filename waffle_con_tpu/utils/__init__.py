"""Host-side utilities: queue tracking, synthetic data, fixtures."""

from waffle_con_tpu.utils.pqueue import CapacityFullError, PQueueTracker, SetPriorityQueue

__all__ = ["CapacityFullError", "PQueueTracker", "SetPriorityQueue"]
