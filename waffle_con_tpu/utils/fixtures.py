"""Golden-fixture loaders for the JSON scenario fixtures in ``tests/data``.

Equivalents of the reference's CSV test loaders: the first zero-edit record
per consensus id is the ground-truth consensus (optionally also fed back in
as a read), the ``edits`` column gives expected per-read distances
(squared under L2).  Parity:
``/root/reference/src/dual_consensus.rs:1400-1461`` (dual) and
``/root/reference/src/priority_consensus.rs:382-489`` (priority chains).
"""

from __future__ import annotations

import json
import pathlib
from typing import List, Optional, Tuple

from waffle_con_tpu.config import ConsensusCost
from waffle_con_tpu.models.consensus import Consensus
from waffle_con_tpu.models.dual_consensus import DualConsensus
from waffle_con_tpu.models.priority_consensus import PriorityConsensus

DATA_DIR = pathlib.Path(__file__).resolve().parent.parent.parent / "tests" / "data"


def _load_records(name: str):
    with open(DATA_DIR / f"{name}.json") as fh:
        return json.load(fh)["records"]


def load_dual_fixture(
    name: str, include_consensus: bool, cost_mode: ConsensusCost
) -> Tuple[List[bytes], DualConsensus]:
    """Returns ``(sequences, expected DualConsensus)``; the expected score
    vectors are unset (equality ignores them)."""
    sequences: List[bytes] = []
    is_consensus1: List[bool] = []
    ed1: List[int] = []
    ed2: List[int] = []
    con1: Optional[bytes] = None
    con2: Optional[bytes] = None

    for record in _load_records(name):
        is_con1 = record["consensus"] == 1
        edits = cost_mode.apply(record["edits"])
        sequence = record["chain"][0].encode()

        if is_con1:
            if con1 is None and edits == 0:
                con1 = sequence
                if not include_consensus:
                    continue
            ed1.append(edits)
        else:
            if con2 is None and edits == 0:
                con2 = sequence
                if not include_consensus:
                    continue
            ed2.append(edits)
        is_consensus1.append(is_con1)
        sequences.append(sequence)

    assert con2 is None or con1 < con2
    consensus1 = Consensus(con1, cost_mode, ed1)
    consensus2 = Consensus(con2, cost_mode, ed2) if con2 is not None else None
    expected = DualConsensus(
        consensus1,
        consensus2,
        is_consensus1,
        [None] * len(sequences),
        [None] * len(sequences),
    )
    return sequences, expected


def load_priority_fixture(
    name: str, include_consensus: bool, cost_mode: ConsensusCost
) -> Tuple[List[List[bytes]], PriorityConsensus]:
    """Returns ``(sequence_chains, expected PriorityConsensus)``; expected
    chain scores are unset (the runner compares sequences/assignments)."""
    consensuses: List[List[bytes]] = []
    sequence_chains: List[List[bytes]] = []
    sequence_indices: List[int] = []

    for record in _load_records(name):
        assert record["consensus"] >= 1
        con_index = record["consensus"] - 1
        edits = cost_mode.apply(record["edits"])
        chain = [s.encode() for s in record["chain"]]

        while con_index >= len(consensuses):
            consensuses.append([])
        if edits == 0 and not consensuses[con_index]:
            consensuses[con_index] = chain
            if not include_consensus:
                continue
        sequence_chains.append(chain)
        sequence_indices.append(con_index)

    assert all(consensuses)
    assert all(sequence_chains)

    # remap consensus ids into lexicographic chain order
    order = sorted(range(len(consensuses)), key=lambda i: consensuses[i])
    lookup = [0] * len(consensuses)
    for new_index, old_index in enumerate(order):
        lookup[old_index] = new_index
    consensuses = [consensuses[i] for i in order]
    sequence_indices = [lookup[i] for i in sequence_indices]

    expected = PriorityConsensus(
        [
            [Consensus(c, cost_mode, []) for c in chain]
            for chain in consensuses
        ],
        sequence_indices,
    )
    return sequence_chains, expected
