"""Search-queue infrastructure for the consensus engines.

Two pieces:

* :class:`PQueueTracker` — beam/threshold accounting sidecar (capability
  parity with ``/root/reference/src/pqueue_tracker.rs:10-144``): histogram
  of queued consensus lengths above a rising threshold, plus per-length
  processed-node capacities.
* :class:`SetPriorityQueue` — a max-priority queue with *set semantics*
  (one entry per key), replacing the reference's ``priority-queue`` crate:
  the engines rely on pushes of an already-present node being detectable
  (``/root/reference/src/dual_consensus.rs:648,678,731`` asserts they never
  happen).  Ties on priority pop in FIFO order, which is deterministic.
"""

from __future__ import annotations

import heapq
from typing import Any, Dict, Hashable, List, Optional, Tuple

import numpy as np


class CapacityFullError(Exception):
    """Raised by :meth:`PQueueTracker.process` when a length is at capacity."""


class PQueueTracker:
    """Tracks how many queued items of each consensus length remain above a
    monotonically rising length threshold, and how many items of each
    length have been processed (with a per-length capacity)."""

    def __init__(self, initial_size: int, capacity_per_size: int) -> None:
        self._length_counts: List[int] = [0] * initial_size
        self._total_count = 0
        self._threshold = 0
        self._processed_counts: List[int] = [0] * initial_size
        self._capacity_per_size = capacity_per_size

    def insert(self, value: int) -> None:
        if value >= len(self._length_counts):
            self._length_counts.extend([0] * (value + 1 - len(self._length_counts)))
        self._length_counts[value] += 1
        if value >= self._threshold:
            self._total_count += 1

    def remove(self, value: int) -> None:
        assert self._length_counts[value] > 0
        self._length_counts[value] -= 1
        if value >= self._threshold:
            assert self._total_count > 0
            self._total_count -= 1

    def increment_threshold(self) -> None:
        self.increase_threshold(self._threshold + 1)

    def increase_threshold(self, new_threshold: int) -> None:
        assert new_threshold >= self._threshold
        for t in range(self._threshold, new_threshold):
            if t < len(self._length_counts):
                self._total_count -= self._length_counts[t]
        self._threshold = new_threshold

    def process(self, value: int) -> None:
        """Mark one item of this length processed; error when full."""
        if value >= len(self._processed_counts):
            self._processed_counts.extend(
                [0] * (value + 1 - len(self._processed_counts))
            )
        if self._processed_counts[value] >= self._capacity_per_size:
            raise CapacityFullError("Capacity is full")
        self._processed_counts[value] += 1

    def processed(self, value: int) -> int:
        if value >= len(self._processed_counts):
            return 0
        return self._processed_counts[value]

    def bulk_run_advance(
        self, start_len: int, steps: int, fresh_pop: bool = True
    ) -> bool:
        """Apply the net tracker effect of a constriction-free frontier
        run segment: ``steps`` consecutive (pop at ``L``, process ``L``,
        insert ``L+1``) cycles starting at ``start_len``, where every
        intermediate insert is immediately consumed by the next pop.
        ``fresh_pop`` False means the segment continues an earlier one,
        so its first cycle pops (removes) the entry the previous
        segment's final insert queued.  Returns False (and applies
        nothing) if any touched length is at processing capacity — the
        caller falls back to the exact scalar loop.  All lengths must be
        at or above the threshold (true for any run: pops below the
        threshold are discarded, not run)."""
        if steps <= 0:
            return True
        end = start_len + steps  # exclusive of the final inserted length
        if end >= len(self._processed_counts):
            self._processed_counts.extend(
                [0] * (end + 1 - len(self._processed_counts))
            )
        window = np.asarray(self._processed_counts[start_len:end])
        if window.max(initial=0) >= self._capacity_per_size:
            return False
        self._processed_counts[start_len:end] = (window + 1).tolist()
        if not fresh_pop:
            self.remove(start_len)
        # intermediate inserts at start_len+1 .. end-1 are each consumed
        # by the following pop, so length_counts only nets the final one
        self.insert(end)
        return True

    def at_capacity(self, value: int) -> bool:
        return self.processed(value) >= self._capacity_per_size

    def __len__(self) -> int:
        return self._total_count

    def unfiltered_len(self) -> int:
        return sum(self._length_counts)

    def is_empty(self) -> bool:
        return self._total_count == 0

    def threshold(self) -> int:
        return self._threshold

    #: horizon for the scalar fallback simulation: a run that commits this
    #: many steps stops with the step-limit code and simply re-engages at
    #: its next pop, so capping the preview costs one extra dispatch at
    #: worst — while an uncapped scalar loop was measured at 82% of the
    #: dual engine's wall time
    SIM_HORIZON = 256

    def simulate_run_bound(
        self,
        start_len: int,
        farthest: int,
        last_constraint: int,
        max_queue_size: int,
        max_nodes_wo_constraint: int,
        max_steps: int,
    ) -> int:
        """Exact preview of how many consecutive frontier pops a
        just-popped node of length ``start_len`` could survive before the
        threshold or per-length capacity bookkeeping would prune it,
        assuming no other queue activity — which is exactly the state of
        affairs during a device-resident extension run.  Lets the run
        engage on nodes *behind* the farthest frontier without risking a
        replayed step the real search would have pruned.

        Fast path: for a node at the frontier (``start_len >= farthest``)
        the threshold can never overtake the run — constriction raises it
        at most to ``farthest``, which trails the run's own lengths — so
        the only possible cut is a capacity-saturated length, found with
        one vectorized scan of the processed-counts window."""
        if start_len >= farthest:
            pc = self._processed_counts
            cap = self._capacity_per_size
            lo = start_len + 1
            hi = min(start_len + max_steps, len(pc))
            if lo < hi:
                window = np.asarray(pc[lo:hi]) >= cap
                j = int(np.argmax(window))
                if window[j]:
                    return j + 1  # first saturated length is step j+1
            return max_steps
        max_steps = min(max_steps, self.SIM_HORIZON)
        lc = list(self._length_counts)
        pc = list(self._processed_counts)
        total = self._total_count
        thr = self._threshold
        cap = self._capacity_per_size
        for j in range(max_steps):
            length = start_len + j
            if j > 0:
                while (
                    total > max_queue_size
                    or last_constraint >= max_nodes_wo_constraint
                ) and thr < farthest:
                    if thr < len(lc):
                        total -= lc[thr]
                    thr += 1
                    last_constraint = 0
                if length < thr:
                    return j
                if length < len(pc) and pc[length] >= cap:
                    return j
                # remove(length): the node leaves the queue for this pop
                if length < len(lc) and lc[length] > 0:
                    lc[length] -= 1
                    if length >= thr:
                        total -= 1
            farthest = max(farthest, length)
            last_constraint += 1
            while length >= len(pc):
                pc.append(0)
            pc[length] += 1
            # insert(length + 1): the extended node re-enters the queue
            while length + 1 >= len(lc):
                lc.append(0)
            lc[length + 1] += 1
            if length + 1 >= thr:
                total += 1
        return max_steps

    def occupancy(self, value: int) -> int:
        if value >= len(self._length_counts):
            return 0
        return self._length_counts[value]

    @property
    def capacity_per_size(self) -> int:
        return self._capacity_per_size

    def export_windows(self, length: int):
        """Length-count and processed-count arrays padded/truncated to
        ``length`` (device-side pop simulation input; see
        ``ops/jax_scorer._j_arena``)."""
        lc = np.zeros(length, dtype=np.int32)
        pc = np.zeros(length, dtype=np.int32)
        n = min(length, len(self._length_counts))
        lc[:n] = self._length_counts[:n]
        m = min(length, len(self._processed_counts))
        pc[:m] = self._processed_counts[:m]
        return lc, pc

    # -- checkpoint/restore seam (models/checkpoint.py) ----------------

    def export_state(self) -> dict:
        """JSON-serializable full state for a search checkpoint."""
        return {
            "length_counts": list(self._length_counts),
            "total_count": self._total_count,
            "threshold": self._threshold,
            "processed_counts": list(self._processed_counts),
            "capacity_per_size": self._capacity_per_size,
        }

    def restore_state(self, state: dict) -> None:
        """Overwrite this tracker with an :meth:`export_state` snapshot.

        The capacity must match the one this tracker was constructed
        with (it comes from the same config), so a checkpoint can never
        smuggle in different beam semantics."""
        if int(state["capacity_per_size"]) != self._capacity_per_size:
            raise ValueError(
                "tracker capacity mismatch: checkpoint "
                f"{state['capacity_per_size']} vs config "
                f"{self._capacity_per_size}"
            )
        self._length_counts = [int(v) for v in state["length_counts"]]
        self._total_count = int(state["total_count"])
        self._threshold = int(state["threshold"])
        self._processed_counts = [
            int(v) for v in state["processed_counts"]
        ]


class SetPriorityQueue:
    """Max-priority queue keyed by hashable identity.

    ``push`` returns ``False`` (and leaves the queue unchanged apart from
    updating the stored payload/priority) when the key is already present —
    the engines assert this never fires, mirroring the reference's
    duplicate-node invariant.  Pop order: highest priority first; equal
    priorities pop in insertion order.
    """

    def __init__(self) -> None:
        # heap entries: (neg_priority_tuple, seq, key)
        self._heap: List[Tuple[Any, int, Hashable]] = []
        self._live: Dict[Hashable, Tuple[Any, Any]] = {}  # key -> (priority, item)
        self._seq = 0

    def __len__(self) -> int:
        return len(self._live)

    def is_empty(self) -> bool:
        return not self._live

    def push(self, key: Hashable, item: Any, priority: Tuple) -> bool:
        """Insert ``item`` with ``priority`` (a tuple where larger wins).

        Returns True if the key was new.  When the key is already present
        the queue is left untouched and False is returned, so the caller
        still owns (and must dispose of) the rejected item.
        """
        if key in self._live:
            return False
        self._live[key] = (priority, item)
        heapq.heappush(self._heap, (self._negate(priority), self._seq, key))
        self._seq += 1
        return True

    def peek_priority(self) -> Optional[Tuple]:
        """Priority of the current best entry, or None when empty."""
        while self._heap:
            _neg, _seq, key = self._heap[0]
            if key in self._live:
                return self._live[key][0]
            heapq.heappop(self._heap)
        return None

    def peek_top(self, k: int) -> List[Tuple[Any, Tuple]]:
        """Up to ``k`` best ``(item, priority)`` pairs in pop order,
        without removing them (used for speculative expansion and
        frontier ganging).

        Partial selection: the backing array is a binary heap, so the
        next-best candidates are reachable by walking it as a tree with
        an auxiliary frontier heap — O(k log k) comparisons per call
        instead of the O(n log k) full scan ``heapq.nsmallest`` costs,
        which scaled every pop with queue depth on deep tie-heavy
        queues.  Stale entries (already popped keys) are skipped but
        their subtrees are still expanded, since a stale parent still
        heap-dominates its children."""
        out: List[Tuple[Any, Tuple]] = []
        if k <= 0 or not self._live:
            return out
        heap = self._heap
        # drain stale entries off the root so repeated peeks stay cheap
        while heap and heap[0][2] not in self._live:
            heapq.heappop(heap)
        if not heap:  # pragma: no cover - _live nonempty implies a root
            return out
        n = len(heap)
        # (entry, index) pairs: entries order by (neg_priority, seq) and
        # seq is unique, so comparison never reaches index or key —
        # emission order is exactly pop order
        frontier: List[Tuple[Tuple[Any, int, Hashable], int]] = [(heap[0], 0)]
        while frontier and len(out) < k:
            entry, i = heapq.heappop(frontier)
            live = self._live.get(entry[2])
            if live is not None:
                out.append((live[1], live[0]))
            left = 2 * i + 1
            if left < n:
                heapq.heappush(frontier, (heap[left], left))
            if left + 1 < n:
                heapq.heappush(frontier, (heap[left + 1], left + 1))
        return out

    def pop(self) -> Tuple[Any, Any]:
        """Remove and return ``(item, priority)`` of the best entry."""
        return self.pop_with_seq()[:2]

    def pop_with_seq(self) -> Tuple[Any, Any, int]:
        """Like :meth:`pop` but also returns the entry's insertion
        sequence number, so a *speculative* pop can be undone with
        :meth:`push_restored` without disturbing FIFO tie order."""
        while self._heap:
            _neg, seq, key = heapq.heappop(self._heap)
            entry = self._live.get(key)
            if entry is None:
                continue  # stale (already popped)
            priority, item = entry
            del self._live[key]
            return item, priority, seq
        raise IndexError("pop from empty SetPriorityQueue")

    def push_restored(
        self, key: Hashable, item: Any, priority: Tuple, seq: int
    ) -> bool:
        """Re-insert a speculatively popped entry with its ORIGINAL
        sequence number: ties against entries inserted after the original
        push still pop this entry first, exactly as if the speculative
        pop never happened."""
        if key in self._live:
            return False
        self._live[key] = (priority, item)
        heapq.heappush(self._heap, (self._negate(priority), seq, key))
        return True

    # -- checkpoint/restore seam (models/checkpoint.py) ----------------

    def export_entries(self) -> List[Tuple[Hashable, Any, Tuple, int]]:
        """Every live entry as ``(key, item, priority, seq)`` in exact
        pop order (priority first, insertion sequence breaking ties).

        Re-inserting each entry into a fresh queue with
        :meth:`push_restored` (then :meth:`restore_seq`) reproduces this
        queue's pop order bit-for-bit, including FIFO tie order."""
        out: List[Tuple[Hashable, Any, Tuple, int]] = []
        seen = set()
        for neg, seq, key in sorted(self._heap):
            if key in seen or key not in self._live:
                continue  # stale entry from a speculative pop/re-push
            seen.add(key)
            priority, item = self._live[key]
            out.append((key, item, priority, seq))
        return out

    def export_seq(self) -> int:
        """The insertion-sequence counter (monotonic push count)."""
        return self._seq

    def restore_seq(self, seq: int) -> None:
        """Advance the insertion-sequence counter to at least ``seq`` so
        future pushes tie-break after every restored entry."""
        self._seq = max(self._seq, int(seq))

    @staticmethod
    def _negate(priority: Tuple) -> Tuple:
        return tuple(-p for p in priority)
