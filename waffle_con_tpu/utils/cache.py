"""Persistent XLA compilation cache.

The scorer kernels recompile per geometry (band width, slot count, read
count); the cache makes those compiles one-time per machine rather than
per process — important on TPU where a single compile can take tens of
seconds."""

from __future__ import annotations

import hashlib
import os
import platform


def _host_fingerprint() -> str:
    """A digest of everything that shapes an XLA:CPU AOT executable's
    machine-code compatibility.  Loading an entry produced under a
    different configuration can SIGILL/segfault inside the cache
    loader (observed live twice: a cache populated on an AVX512-full
    machine crashed a smaller host, and entries written by
    TPU-attached processes — whose terminal-injected ``XLA_FLAGS``
    change the CPU codegen tuning, e.g. ``prefer-no-scatter`` — later
    crashed pure-CPU runs on the SAME host).  Scoping the directory by
    CPU flags + jax/jaxlib version + ambient XLA env makes that
    pollution structurally impossible."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
            else:
                feats = platform.processor()
    except OSError:  # pragma: no cover - non-Linux fallback
        feats = platform.processor()
    import jax

    feats += "|" + jax.__version__
    feats += "|" + os.environ.get("XLA_FLAGS", "")
    feats += "|" + os.environ.get("LIBTPU_INIT_ARGS", "")
    # TPU-attached processes compile their host-side CPU executables
    # under terminal-injected codegen flags that leave no trace in this
    # process's env; the resolved platform selection is the reliable
    # discriminator (reading the config does NOT initialize a backend)
    feats += "|" + str(
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS", "")
    )
    return hashlib.sha256(feats.encode()).hexdigest()[:12]


def enable_compilation_cache(path: str | None = None) -> None:
    """Point JAX's persistent compilation cache at ``path`` (default
    ``$JAX_CACHE_DIR`` or ``~/.cache/waffle_con_tpu_jax-<cpu-digest>``).
    Safe to call multiple times."""
    import jax

    if path is None:
        path = os.environ.get(
            "JAX_CACHE_DIR",
            os.path.join(
                os.path.expanduser("~"),
                ".cache",
                f"waffle_con_tpu_jax-{_host_fingerprint()}",
            ),
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
