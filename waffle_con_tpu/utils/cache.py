"""Persistent XLA compilation cache.

The scorer kernels recompile per geometry (band width, slot count, read
count); the cache makes those compiles one-time per machine rather than
per process — important on TPU where a single compile can take tens of
seconds."""

from __future__ import annotations

import os


def enable_compilation_cache(path: str | None = None) -> None:
    """Point JAX's persistent compilation cache at ``path`` (default
    ``$JAX_CACHE_DIR`` or ``~/.cache/waffle_con_tpu_jax``).  Safe to call
    multiple times."""
    import jax

    if path is None:
        path = os.environ.get(
            "JAX_CACHE_DIR",
            os.path.join(
                os.path.expanduser("~"), ".cache", "waffle_con_tpu_jax"
            ),
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
