"""Persistent XLA compilation cache.

The scorer kernels recompile per geometry (band width, slot count, read
count); the cache makes those compiles one-time per machine rather than
per process — important on TPU where a single compile can take tens of
seconds."""

from __future__ import annotations

import hashlib
import os
import platform


def _host_fingerprint() -> str:
    """A digest of the host CPU's feature set.  XLA:CPU caches AOT
    machine code for the COMPILING host; loading it on a host missing
    any of those features can SIGILL (observed live: a cache populated
    on an AVX512-full machine crashed the test suite on a smaller one).
    Scoping the cache directory by this fingerprint makes cross-host
    pollution structurally impossible."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
            else:
                feats = platform.processor()
    except OSError:  # pragma: no cover - non-Linux fallback
        feats = platform.processor()
    return hashlib.sha256(feats.encode()).hexdigest()[:12]


def enable_compilation_cache(path: str | None = None) -> None:
    """Point JAX's persistent compilation cache at ``path`` (default
    ``$JAX_CACHE_DIR`` or ``~/.cache/waffle_con_tpu_jax-<cpu-digest>``).
    Safe to call multiple times."""
    import jax

    if path is None:
        path = os.environ.get(
            "JAX_CACHE_DIR",
            os.path.join(
                os.path.expanduser("~"),
                ".cache",
                f"waffle_con_tpu_jax-{_host_fingerprint()}",
            ),
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
