"""Persistent XLA compilation cache.

The scorer kernels recompile per geometry (band width, slot count, read
count); the cache makes those compiles one-time per machine rather than
per process — important on TPU where a single compile can take tens of
seconds.

Entries are integrity-checked: a JSON manifest of content hashes rides
next to the entries, and :func:`quarantine_corrupt_entries` moves any
entry whose bytes no longer match (crashed writer, disk fault, injected
corruption) into a ``_quarantine/`` subdirectory before JAX can load
it — a quarantined kernel recompiles; a loaded corrupt one can segfault
the process."""

from __future__ import annotations

import hashlib
import json
import logging
import os
import platform
import shutil

logger = logging.getLogger(__name__)

#: manifest + quarantine live inside the cache dir; both invisible to
#: JAX's entry scan (it only loads exact key filenames)
MANIFEST_NAME = "MANIFEST.json"
QUARANTINE_DIR = "_quarantine"


def _host_fingerprint() -> str:
    """A digest of everything that shapes an XLA:CPU AOT executable's
    machine-code compatibility.  Loading an entry produced under a
    different configuration can SIGILL/segfault inside the cache
    loader (observed live twice: a cache populated on an AVX512-full
    machine crashed a smaller host, and entries written by
    TPU-attached processes — whose terminal-injected ``XLA_FLAGS``
    change the CPU codegen tuning, e.g. ``prefer-no-scatter`` — later
    crashed pure-CPU runs on the SAME host).  Scoping the directory by
    CPU flags + jax/jaxlib version + ambient XLA env makes that
    pollution structurally impossible."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
            else:
                feats = platform.processor()
    except OSError:  # pragma: no cover - non-Linux fallback
        feats = platform.processor()
    import jax

    feats += "|" + jax.__version__
    feats += "|" + os.environ.get("XLA_FLAGS", "")
    feats += "|" + os.environ.get("LIBTPU_INIT_ARGS", "")
    # TPU-attached processes compile their host-side CPU executables
    # under terminal-injected codegen flags that leave no trace in this
    # process's env; the resolved platform selection is the reliable
    # discriminator (reading the config does NOT initialize a backend)
    feats += "|" + str(
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS", "")
    )
    return hashlib.sha256(feats.encode()).hexdigest()[:12]


def _sha256_file(path: str) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def _cache_entries(path: str):
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if name == MANIFEST_NAME or name.startswith("."):
            continue
        # JAX's LRU eviction keeps an 8-byte ``<key>-atime`` sidecar per
        # entry and REWRITES it on every cache hit; it carries no machine
        # code, so sealing it would quarantine every sidecar on every
        # warm run (observed: ~28 spurious quarantine events per bench)
        if name.endswith("-atime"):
            continue
        if os.path.isfile(full):
            yield name, full


def _load_manifest(path: str) -> dict:
    manifest_path = os.path.join(path, MANIFEST_NAME)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        if not isinstance(manifest, dict):
            raise ValueError("manifest is not a mapping")
        return manifest
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as exc:
        # a corrupt manifest is rebuilt from the surviving entries; the
        # entries it would have vouched for get re-sealed below
        logger.warning("rebuilding corrupt cache manifest: %r", exc)
        return {}


def _save_manifest(path: str, manifest: dict) -> None:
    manifest_path = os.path.join(path, MANIFEST_NAME)
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=0, sort_keys=True)
    os.replace(tmp, manifest_path)


def quarantine_corrupt_entries(path: str) -> list:
    """Verify every cache entry against the manifest; move mismatches
    into ``_quarantine/`` (so the kernel recompiles instead of loading
    corrupt machine code) and seal new entries into the manifest.
    Returns the quarantined entry names."""
    manifest = _load_manifest(path)
    quarantined = []
    changed = False
    for name, full in _cache_entries(path):
        digest = _sha256_file(full)
        expected = manifest.get(name)
        if expected is None:
            manifest[name] = digest
            changed = True
            continue
        if digest != expected:
            qdir = os.path.join(path, QUARANTINE_DIR)
            os.makedirs(qdir, exist_ok=True)
            shutil.move(full, os.path.join(qdir, name))
            del manifest[name]
            changed = True
            quarantined.append(name)
            logger.warning(
                "quarantined corrupt compilation-cache entry %s "
                "(hash mismatch); it will recompile", name,
            )
            from waffle_con_tpu.runtime import events

            events.record("cache_quarantine", entry=name)
    # drop manifest rows whose entries vanished (evicted externally) and
    # rows for ``-atime`` sidecars sealed by an older manifest format
    for name in list(manifest):
        if name.endswith("-atime") or not os.path.isfile(
            os.path.join(path, name)
        ):
            del manifest[name]
            changed = True
    if changed:
        _save_manifest(path, manifest)
    if quarantined:
        from waffle_con_tpu.obs import flight

        flight.trigger(
            "cache_quarantine", cache_dir=path,
            entries=list(quarantined),
        )
    return quarantined


def enable_compilation_cache(path: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``path`` (default
    ``$JAX_CACHE_DIR`` or ``~/.cache/waffle_con_tpu_jax-<cpu-digest>``),
    after integrity-checking the entries already there.  Safe to call
    multiple times.  Returns the cache directory."""
    import jax

    if path is None:
        path = os.environ.get(
            "JAX_CACHE_DIR",
            os.path.join(
                os.path.expanduser("~"),
                ".cache",
                f"waffle_con_tpu_jax-{_host_fingerprint()}",
            ),
        )
    os.makedirs(path, exist_ok=True)
    from waffle_con_tpu.runtime import faults

    faults.maybe_corrupt_cache(path)
    quarantine_corrupt_entries(path)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path
