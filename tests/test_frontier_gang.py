"""Frontier-parallel speculation: gang the top-M search branches
through the ragged kernel.

The contract under test is absolute: for EVERY gang width M — explicit
(``WAFFLE_FRONTIER_M`` / ``frontier_width``) or adaptive — every engine
produces results byte-identical to M=1 and to the Python oracle,
because peer advances deposit as consume-once injections that are
validated against the real pop's arguments and invalidated whenever
the branch's slot mutates outside the speculated run (push / activate /
arena / free / supervisor demotion).  The adaptive policy itself is
pure (any width it returns is byte-safe), so it is unit-tested
directly; the deposit seam is exercised at the scorer level where the
invalidation hooks are observable."""

import types

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
    PriorityConsensusDWFA,
)
from waffle_con_tpu.models.frontier import FrontierSpeculator, explicit_width
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.ops import ragged as _ragged
from waffle_con_tpu.ops.ragged import GangMember
from waffle_con_tpu.runtime import events
from waffle_con_tpu.utils.example_gen import corrupt, generate_test

BIG = 2**31 - 1


# ------------------------------------------------------------ workloads


def _noisy_reads():
    """2% noise at depth 8: pops fall off the arena fast path onto the
    forced run_extend path where gangs launch (and commit)."""
    _, reads = generate_test(4, 300, 8, 0.02, seed=52300)
    return reads


def _tie_reads(seq_len=160, n=8, flips=6, seed=41000):
    """Exact 50/50 vote ties at `flips` positions: the queue holds a
    deep flat frontier (gap 0) of near-tied branches throughout."""
    rng = np.random.default_rng(seed)
    truth, reads = generate_test(4, seq_len, n, 0.0, seed=seed + 1)
    reads = [bytearray(r) for r in reads]
    for pos in rng.choice(seq_len, size=flips, replace=False):
        alt = (truth[pos] + 1 + int(rng.integers(3))) % 4
        for i in range(n // 2):
            reads[i][pos] = alt
    return [bytes(r) for r in reads]


def _dual_reads():
    rng = np.random.default_rng(61250)
    truth, reads1 = generate_test(4, 250, 5, 0.04, seed=61251)
    h2 = bytearray(truth)
    for pos in rng.choice(250, size=3, replace=False):
        h2[pos] = (h2[pos] + 1 + int(rng.integers(3))) % 4
    return list(reads1) + [
        corrupt(bytes(h2), 0.04, np.random.default_rng(61252 + i))
        for i in range(5)
    ]


def _chains():
    n = 8
    t0, level0 = generate_test(4, 60, n, 0.02, seed=71000)
    t1a, _ = generate_test(4, 100, 1, 0.0, seed=71001)
    t1b = bytearray(t1a)
    t1b[50] = (t1b[50] + 1) % 4
    t1b = bytes(t1b)
    return [
        [level0[i],
         corrupt(t1a if i < n // 2 else t1b, 0.02,
                 np.random.default_rng(71002 + i))]
        for i in range(n)
    ]


def _cfg(backend, min_count=2):
    return (
        CdwfaConfigBuilder().backend(backend).min_count(min_count).build()
    )


def _run_single(backend, reads, m, monkeypatch, min_count=2):
    monkeypatch.setenv("WAFFLE_FRONTIER_M", str(m))
    e = ConsensusDWFA(_cfg(backend, min_count))
    for r in reads:
        e.add_sequence(r)
    res = [(c.sequence, c.scores) for c in e.consensus()]
    return res, dict(e.last_search_stats.get("scorer_counters", {}))


def _run_dual(backend, reads, m, monkeypatch, min_count=2):
    monkeypatch.setenv("WAFFLE_FRONTIER_M", str(m))
    e = DualConsensusDWFA(_cfg(backend, min_count))
    for r in reads:
        e.add_sequence(r)
    res = e.consensus()
    return res, dict(e.last_search_stats.get("scorer_counters", {}))


# the python oracle and the jax M=1 baseline are M-independent: compute
# each expensive reference once per module, not once per parametrization
_REF = {}


def _ref(key, thunk):
    if key not in _REF:
        _REF[key] = thunk()
    return _REF[key]


# ----------------------------------------------------- width policy unit


def test_explicit_width_env(monkeypatch):
    monkeypatch.delenv("WAFFLE_FRONTIER_M", raising=False)
    assert explicit_width() is None
    monkeypatch.setenv("WAFFLE_FRONTIER_M", "4")
    assert explicit_width() == 4
    monkeypatch.setenv("WAFFLE_FRONTIER_M", "0")
    assert explicit_width() == 1  # 0 means disabled == serial
    monkeypatch.setenv("WAFFLE_FRONTIER_M", "garbage")
    assert explicit_width() is None


def test_config_frontier_width_knob(monkeypatch):
    monkeypatch.delenv("WAFFLE_FRONTIER_M", raising=False)
    cfg = CdwfaConfigBuilder().frontier_width(6).build()
    sp = FrontierSpeculator(object(), cfg)
    assert sp.width(100, 0) == 6
    # env wins over the config knob, and clamps to the gang capacity
    monkeypatch.setenv("WAFFLE_FRONTIER_M", "99")
    sp = FrontierSpeculator(object(), cfg)
    assert sp.width(100, 0) == FrontierSpeculator.MAX_M


def test_config_frontier_width_validation():
    with pytest.raises(ValueError):
        CdwfaConfigBuilder().frontier_width(0).build()


def test_width_policy_adaptive(monkeypatch):
    monkeypatch.delenv("WAFFLE_FRONTIER_M", raising=False)
    sp = FrontierSpeculator(object())
    # thin queue: stay serial
    assert sp.width(0, None) == 1
    assert sp.width(3, 0) == 1
    # positive best-vs-next gap: the next pops are not ties
    assert sp.width(64, 2) == 1
    # flat deep frontier: widen with depth, capped at the gang size
    assert sp.width(4, 0) == 2
    assert sp.width(8, 0) == 4
    assert sp.width(16, None) == 8
    assert sp.width(1000, 0) == FrontierSpeculator.MAX_M
    assert sp.last_width == FrontierSpeculator.MAX_M


def test_width_policy_cooldown(monkeypatch):
    monkeypatch.delenv("WAFFLE_FRONTIER_M", raising=False)
    sp = FrontierSpeculator(object())
    # a window of resolutions with a rotten commit rate trips a cooldown
    sp._js = types.SimpleNamespace(
        counters={"run_gang_injected": 1, "run_gang_mispredict": 63}
    )
    assert sp.width(64, 0) == 1
    assert sp._cooldown == FrontierSpeculator.COOLDOWN_POPS
    for _ in range(FrontierSpeculator.COOLDOWN_POPS):
        assert sp.width(64, 0) == 1
    # cooldown expired AND the window was reset: speculation resumes
    assert sp.width(64, 0) == FrontierSpeculator.MAX_M


# ------------------------------------------------- deposit seam (scorer)


def _two_root_gang(reads, max_steps=32):
    from waffle_con_tpu.ops.jax_scorer import JaxScorer

    sc = JaxScorer(reads, _cfg("jax"))
    n = len(reads)
    h1 = sc.root(np.ones(n, dtype=bool))
    h2 = sc.root(np.ones(n, dtype=bool))
    gang = _ragged.frontier_gang_for(sc)
    deposits = gang.run(
        [
            GangMember(h1, b"", BIG, BIG, 0, max_steps),
            GangMember(h2, b"", BIG, BIG, 0, max_steps),
        ],
        2,
        False,
    )
    return sc, gang, h1, h2, deposits


def test_gang_deposit_consume_and_free():
    """A gang deposit is consumed verbatim by the matching run_extend
    call (injected, byte-identical to a solo run) and invalidated by
    free() — a freed-then-reused handle can never see stale state."""
    from waffle_con_tpu.ops.jax_scorer import JaxScorer

    _, reads = generate_test(4, 200, 6, 0.0, seed=81000)
    sc, gang, h1, h2, deposits = _two_root_gang(reads)
    assert deposits == 2
    assert gang.pending(h1) and gang.pending(h2)

    steps, code, appended, _stats, _recs = sc.run_extend(
        h1, b"", BIG, BIG, 0, 2, False, 32
    )
    assert sc.counters.get("run_gang_injected", 0) == 1
    assert not gang.pending(h1)

    # reference: an identical scorer running the same call solo
    ref = JaxScorer(reads, _cfg("jax"))
    g = ref.root(np.ones(len(reads), dtype=bool))
    rsteps, rcode, rappended, _s, _r = ref.run_extend(
        g, b"", BIG, BIG, 0, 2, False, 32
    )
    assert (steps, code, appended) == (rsteps, rcode, rappended)

    # free() drops the peer's deposit before the handle can be reused
    sc.free(h2)
    assert not gang.pending(h2)
    assert gang.counters["dropped"] >= 1


def test_gang_deposit_dropped_on_slot_mutation():
    """Any out-of-band slot mutation (here: a push advancing the
    branch) invalidates that branch's deposit — the held post-state is
    stale — while untouched peers keep theirs."""
    _, reads = generate_test(4, 200, 6, 0.0, seed=82000)
    sc, gang, h1, h2, deposits = _two_root_gang(reads)
    assert deposits == 2
    first = bytes([reads[0][0]])
    sc.push_many([(h1, first)])
    assert not gang.pending(h1)
    assert gang.pending(h2)


def test_gang_deposit_mispredict_falls_back_solo():
    """A deposit whose speculated arguments don't validate against the
    real pop is discarded (mispredict counted) and the solo run from
    the pristine slot returns the exact result."""
    from waffle_con_tpu.ops.jax_scorer import JaxScorer

    _, reads = generate_test(4, 200, 6, 0.0, seed=83000)
    sc, gang, h1, _h2, deposits = _two_root_gang(reads, max_steps=32)
    assert deposits == 2
    # real pop arrives with a TIGHTER budget than speculated: the
    # speculated trajectory may overrun it, so validation must reject
    steps, code, appended, _stats, _recs = sc.run_extend(
        h1, b"", 0, 0, 0, 2, False, 32
    )
    assert sc.counters.get("run_gang_mispredict", 0) == 1
    ref = JaxScorer(reads, _cfg("jax"))
    g = ref.root(np.ones(len(reads), dtype=bool))
    rsteps, rcode, rappended, _s, _r = ref.run_extend(
        g, b"", 0, 0, 0, 2, False, 32
    )
    assert (steps, code, appended) == (rsteps, rcode, rappended)


# ------------------------------------------------ engine parity at every M


@pytest.mark.parametrize("m", [2, 4, 8])
def test_single_engine_m_parity(m, monkeypatch):
    reads = _ref("noisy_reads", _noisy_reads)
    want = _ref(
        "noisy_py",
        lambda: _run_single("python", reads, 1, monkeypatch)[0],
    )
    base = _ref(
        "noisy_jax1",
        lambda: _run_single("jax", reads, 1, monkeypatch)[0],
    )
    got, counters = _run_single("jax", reads, m, monkeypatch)
    assert base == want
    assert got == base
    if m == 4:
        # the gang must actually fire AND commit on this geometry —
        # parity alone could pass with speculation silently disabled
        assert counters.get("gang_groups", 0) > 0
        assert counters.get("run_gang_injected", 0) > 0


@pytest.mark.parametrize("m", [2, 4])
def test_dual_engine_m_parity(m, monkeypatch):
    reads = _ref("dual_reads", _dual_reads)
    want = _ref(
        "dual_py", lambda: _run_dual("python", reads, 1, monkeypatch)[0]
    )
    base = _ref(
        "dual_jax1", lambda: _run_dual("jax", reads, 1, monkeypatch)[0]
    )
    got, counters = _run_dual("jax", reads, m, monkeypatch)
    assert base == want
    assert got == base
    if m == 4:
        assert counters.get("gang_groups", 0) > 0
        assert counters.get("run_gang_injected", 0) > 0


def test_priority_engine_m_parity(monkeypatch):
    chains = _ref("chains", _chains)

    def run(backend, m):
        monkeypatch.setenv("WAFFLE_FRONTIER_M", str(m))
        e = PriorityConsensusDWFA(_cfg(backend))
        for c in chains:
            e.add_sequence_chain(c)
        return e.consensus()

    want = run("python", 1)
    base = run("jax", 1)
    got = run("jax", 4)
    assert base == want
    assert got == base


def test_near_tie_divergence_grid(monkeypatch):
    """Deep 50/50-tie frontiers — the geometry speculation targets and
    the most parity-hostile one (every pop is a coin-flip ordering the
    oracle resolves by FIFO seq): byte-identical at every M."""
    reads = _ref("tie_reads", _tie_reads)
    want = _ref(
        "tie_py",
        lambda: _run_single("python", reads, 1, monkeypatch,
                            min_count=4)[0],
    )
    results = {
        m: _run_single("jax", reads, m, monkeypatch, min_count=4)[0]
        for m in (1, 2, 8)
    }
    assert results[1] == want
    assert results[2] == results[1]
    assert results[8] == results[1]


def test_m_by_k_odd_composition(monkeypatch):
    """Gang width composes with K-column speculative stepping: M=4
    gangs advancing K=5 columns per device iteration (an odd K that
    never divides stop steps evenly) stay byte-identical to M=1,K=1."""
    reads = _ref("noisy_reads", _noisy_reads)
    base = _ref(
        "noisy_jax1",
        lambda: _run_single("jax", reads, 1, monkeypatch)[0],
    )
    monkeypatch.setenv("WAFFLE_RUN_COLS", "5")
    got, _ = _run_single("jax", reads, 4, monkeypatch)
    assert got == base


# ----------------------------------------------- faults / serving seams


@pytest.mark.faultinject
def test_supervisor_demotion_mid_gang(faults, monkeypatch):
    """A mid-search backend demotion under fault injection: every
    pending gang deposit dies with the demoted backend (release_scorer
    drops them) and the migrated search finishes byte-identical."""
    reads = _ref("noisy_reads", _noisy_reads)
    want = _ref(
        "noisy_py",
        lambda: _run_single("python", reads, 1, monkeypatch)[0],
    )
    faults.add("timeout", backend="jax", at=5, count=None)
    faults.add("timeout", backend="jax", at=6, count=None)
    monkeypatch.setenv("WAFFLE_FRONTIER_M", "4")
    cfg = (
        CdwfaConfigBuilder()
        .backend("jax")
        .min_count(2)
        .backend_chain(("python",))
        .dispatch_retries(1)
        .breaker_threshold(2)
        .retry_backoff_s(0.0)
        .build()
    )
    e = ConsensusDWFA(cfg)
    for r in reads:
        e.add_sequence(r)
    got = [(c.sequence, c.scores) for c in e.consensus()]
    demotions = events.get_events("backend_demoted")
    assert [(d["from_backend"], d["to_backend"]) for d in demotions] == [
        ("jax", "python")
    ]
    assert got == want


def test_adaptive_widens_and_collapses(monkeypatch):
    """The acceptance contract for adaptive M, asserted through the
    FrontierSampler flight records the engines publish: deep flat tie
    frontiers widen past 1; thin frontiers never leave 1."""
    monkeypatch.delenv("WAFFLE_FRONTIER_M", raising=False)
    monkeypatch.setenv("WAFFLE_FRONTIER_SAMPLE", "1")

    def widths(reads, min_count):
        obs_flight.reset()
        e = ConsensusDWFA(_cfg("jax", min_count))
        for r in reads:
            e.add_sequence(r)
        e.consensus()
        ws = [
            r["gang_width"]
            for r in obs_flight.get_recorder().records()
            if r["kind"] == "frontier" and "gang_width" in r
        ]
        obs_flight.reset()
        return ws

    deep = widths(_ref("tie_reads", _tie_reads), 4)
    assert max(deep) > 1
    assert min(deep) == 1  # startup/tail frontiers are thin

    _, thin_reads = generate_test(4, 120, 6, 0.01, seed=777)
    thin = widths(thin_reads, 2)
    assert thin and set(thin) == {1}
