"""End-to-end tests for the dual-consensus engine, mirroring the reference
suite (``/root/reference/src/dual_consensus.rs:1352-2056``): splits,
unequal lengths, noise-before-variation, multi-extension, equal-option
ties, tail extension, ed-delta misassignment, and the JSON scenario
fixtures."""

import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    Consensus,
    ConsensusCost,
    DualConsensus,
    DualConsensusDWFA,
)
from waffle_con_tpu.models.consensus import EngineError
from waffle_con_tpu.models.dual_consensus import _DualNode
from waffle_con_tpu.utils.fixtures import load_dual_fixture


def run_fixture(name, include_consensus, config=None):
    if config is None:
        config = CdwfaConfigBuilder().wildcard(ord("*")).build()
    sequences, expected = load_dual_fixture(
        name, include_consensus, config.consensus_cost
    )
    engine = DualConsensusDWFA(config)
    for sequence in sequences:
        engine.add_sequence(sequence)
    assert len(engine.alphabet) == 4
    assert engine.consensus() == [expected]


def dc(consensus1, scores1, consensus2=None, scores2=None, is_consensus1=None):
    n = len(is_consensus1)
    return DualConsensus(
        Consensus(consensus1, ConsensusCost.L1_DISTANCE, scores1),
        Consensus(consensus2, ConsensusCost.L1_DISTANCE, scores2)
        if consensus2 is not None
        else None,
        is_consensus1,
        [None] * n,
        [None] * n,
    )


def test_doc_example():
    sequences = [
        b"TCCGT",
        b"ACCGT",  # consensus 1
        b"ACCGT",  # consensus 1
        b"ACCAT",
        b"CCGTAAT",
        b"CGTAAAT",
        b"CGTAAT",  # consensus 2
        b"CGTAAT",  # consensus 2
    ]
    engine = DualConsensusDWFA()
    for s in sequences:
        engine.add_sequence(s)
    results = engine.consensus()
    assert len(results) == 1
    assert results[0].consensus1 == Consensus(
        b"ACCGT", ConsensusCost.L1_DISTANCE, [1, 0, 0, 1]
    )
    assert results[0].consensus2 == Consensus(
        b"CGTAAT", ConsensusCost.L1_DISTANCE, [1, 1, 0, 0]
    )
    assert results[0].is_consensus1 == [
        True, True, True, True, False, False, False, False,
    ]


def test_single_sequence():
    sequence = b"ACGTACGTACGT"
    engine = DualConsensusDWFA()
    engine.add_sequence(sequence)
    assert len(engine.alphabet) == 4
    assert engine.consensus() == [
        dc(sequence, [0], is_consensus1=[True])
    ]


def test_trio_sequence():
    sequence = b"ACGTACGTACGT"
    sequence2 = b"ACGTACCTACGT"
    engine = DualConsensusDWFA()
    engine.add_sequence(sequence)
    engine.add_sequence(sequence)
    engine.add_sequence(sequence2)
    assert engine.consensus() == [
        dc(sequence, [0, 0, 1], is_consensus1=[True, True, True])
    ]


def test_complicated():
    expected = b"ACGTACGTACGT"
    sequences = [b"ACTACGGTACGT", b"ACGTAAGTCCGT", b"AAGTACGTACGT"]
    engine = DualConsensusDWFA()
    for s in sequences:
        engine.add_sequence(s)
    assert engine.consensus() == [
        dc(expected, [2, 2, 1], is_consensus1=[True] * 3)
    ]


def test_wildcards():
    expected = b"ACGTACGTACGT"
    sequences = [b"ACGTACCGT****", b"**GTATGTAC**", b"****ACGTACGT"]
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().wildcard(ord("*")).build()
    )
    for s in sequences:
        engine.add_sequence(s)
    assert engine.consensus() == [
        dc(expected, [1, 1, 0], is_consensus1=[True] * 3)
    ]


def test_all_wildcards():
    actual = b"*CGTACG*ACG*"
    sequences = [b"*CGTAACG*ACG*", b"*CGTACG*ACG*", b"*CGTACG*ATG*"]
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().wildcard(ord("*")).build()
    )
    for s in sequences:
        engine.add_sequence(s)
    assert engine.consensus() == [
        dc(actual, [1, 0, 1], is_consensus1=[True] * 3)
    ]


def test_dual_sequence():
    sequence = b"ACGT"
    alt = b"AGGT"
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().min_count(1).build()
    )
    engine.add_sequence(sequence)
    engine.add_sequence(alt)
    assert engine.consensus() == [
        dc(sequence, [0], alt, [0], is_consensus1=[True, False])
    ]


@pytest.mark.parametrize(
    "sequence,alt",
    [(b"ACGT", b"AGGTA"), (b"ACGTA", b"AGGT")],
)
def test_dual_unequal(sequence, alt):
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().min_count(1).build()
    )
    engine.add_sequence(sequence)
    engine.add_sequence(alt)
    assert engine.consensus() == [
        dc(sequence, [0], alt, [0], is_consensus1=[True, False])
    ]


def test_dual_noise_before_variation():
    con1 = b"ACGTACGTACGT"
    con2 = b"ACGTACGTCCCT"
    sequences = [
        b"ACGTACGTACGT",
        b"ACCGTACGTACGT",  # noisy C insert
        b"ACGTACGTACGT",
        b"ACGTACGTCCCT",
        b"ACGTACGTCCCT",
        b"ACCGTACGTCCCT",  # noisy C insert
    ]
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().min_count(1).max_queue_size(1000).build()
    )
    for s in sequences:
        engine.add_sequence(s)
    assert engine.consensus() == [
        dc(
            con1,
            [0, 1, 0],
            con2,
            [0, 0, 1],
            is_consensus1=[True, True, True, False, False, False],
        )
    ]


def test_multi_extension():
    con1 = b"ACGTACGTACGT"
    con2 = b"ACGTACGTCCCT"
    sequences = [
        b"ACGTACGTACGT",
        b"ACGTACGTACGT",
        b"ACGTACGTGCGT",  # A read as G: extra extension candidate
        b"ACGTACGTCCCT",
        b"ACGTACGTCCCT",
        b"ACGTACGTGCCT",  # C read as G: extra extension candidate
    ]
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().min_count(1).max_queue_size(1000).build()
    )
    for s in sequences:
        engine.add_sequence(s)
    assert engine.consensus() == [
        dc(
            con1,
            [0, 0, 1],
            con2,
            [0, 0, 1],
            is_consensus1=[True, True, True, False, False, False],
        )
    ]


def test_equal_options():
    sequences = [
        b"ACGTACGTACGT",  # 00
        b"ACGTCCGTCCGT",  # 11
        b"ACGTACGTCCGT",  # 01
        b"ACGTCCGTACGT",  # 10
    ]
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().min_count(1).max_queue_size(1000).build()
    )
    for s in sequences:
        engine.add_sequence(s)
    results = engine.consensus()
    # six equally-good dual splits, each with total ED 2
    assert len(results) == 6
    for r in results:
        assert r.consensus2 is not None
        total = sum(r.consensus1.scores) + sum(r.consensus2.scores)
        assert total == 2


def test_tail_extension():
    # a 1bp tail difference does not create a dual split, only a tie
    con1 = b"ACGT"
    con2 = b"ACGTT"
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().min_count(1).max_queue_size(1000).build()
    )
    engine.add_sequence(con1)
    engine.add_sequence(con2)
    assert engine.consensus() == [
        dc(con1, [0, 1], is_consensus1=[True, True]),
        dc(con2, [1, 0], is_consensus1=[True, True]),
    ]


def test_csv_dual_001():
    run_fixture("dual_001", True)


def test_dual_max_ed_delta():
    # restricting dual_max_ed_delta to 0 mis-assigns the third read
    sequences, expected = load_dual_fixture(
        "dual_001", True, ConsensusCost.L1_DISTANCE
    )
    expected = DualConsensus(
        Consensus(
            expected.consensus1.sequence,
            ConsensusCost.L1_DISTANCE,
            [0, 4, 4, 2],
        ),
        Consensus(
            expected.consensus2.sequence,
            ConsensusCost.L1_DISTANCE,
            [3, 0, 0, 0, 0, 0],
        ),
        [True, True, False, True, True, False, False, False, False, False],
        [None] * 10,
        [None] * 10,
    )
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().wildcard(ord("*")).dual_max_ed_delta(0).build()
    )
    for s in sequences:
        engine.add_sequence(s)
    assert engine.consensus() == [expected]


def test_csv_length_gap_001():
    run_fixture(
        "length_gap_001",
        False,
        CdwfaConfigBuilder()
        .wildcard(ord("*"))
        .min_count(2)
        .dual_max_ed_delta(5)
        .max_queue_size(1000)
        .consensus_cost(ConsensusCost.L2_DISTANCE)
        .build(),
    )


def test_csv_early_termination_001():
    run_fixture(
        "dual_early_termination_001",
        True,
        CdwfaConfigBuilder()
        .wildcard(ord("*"))
        .allow_early_termination(True)
        .build(),
    )


def test_offset_windows():
    expected = b"ACGTACGTACGTACGT"
    sequences = [b"ACGTACGTACGTACGT", b"ACGTACGTACGT", b"GTACGTACGT"]
    offsets = [None, 4, 7]
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().offset_window(1).offset_compare_length(4).build()
    )
    for sequence, offset in zip(sequences, offsets):
        engine.add_sequence_offset(sequence, offset)
    results = engine.consensus()
    assert len(results) == 1
    assert not results[0].is_dual()
    assert results[0].consensus1.sequence == expected
    assert results[0].consensus1.scores == [0, 0, 0]


def test_offset_gap_err():
    sequences = [b"ACGTACGTACGTACGT", b"ACGTACGTACGTACGT"]
    offsets = [None, 1000]
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().offset_window(1).offset_compare_length(4).build()
    )
    for sequence, offset in zip(sequences, offsets):
        engine.add_sequence_offset(sequence, offset)
    with pytest.raises(EngineError) as err:
        engine.consensus()
    assert str(err.value) == "Finalize called on DWFA that was never initialized."


def test_get_ed_weights():
    # unit test of the vote-weight computation
    # (parity: /root/reference/src/dual_consensus.rs:1362-1382)
    import numpy as np

    from waffle_con_tpu.config import CdwfaConfig
    from waffle_con_tpu.ops.scorer import PythonScorer

    sequences = [b"ACGT", b"CGTA"]
    scorer = PythonScorer(sequences, CdwfaConfig(allow_early_termination=True))
    node = _DualNode()
    node.active1 = [True, True]
    node.active2 = [False, False]
    node.offsets1 = [0, 0]
    node.offsets2 = [None, None]
    node.h1 = scorer.root(np.array([True, True]))
    node.stats1 = scorer.stats(node.h1, b"")

    # emulate activate_dual with symbols A and C
    node.is_dual = True
    node.consensus2 = node.consensus1
    node.h2 = scorer.clone(node.h1)
    node.active2 = [True, True]
    node.offsets2 = [0, 0]
    node.consensus1 = b"A"
    node.stats1 = scorer.push(node.h1, b"A")
    node.consensus2 = b"C"
    node.stats2 = scorer.push(node.h2, b"C")

    assert node.ed_weights(True, True) == [1.0 / 1.5, 0.5 / 1.5]
    assert node.ed_weights(False, True) == [0.5 / 1.5, 1.0 / 1.5]
    assert node.ed_weights(True, False) == [1.0, 0.0]
    assert node.ed_weights(False, False) == [0.0, 1.0]
