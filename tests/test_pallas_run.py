"""Parity of the fused pallas run kernel against the XLA while-loop path.

The pallas kernel (ops/pallas_run.py) re-derives ``_j_run`` as one
Mosaic kernel; these tests run it in interpret mode on the CPU backend
and require decision-for-decision identical results — steps, stop code,
appended symbols, the full stats snapshot, and absorbed records — on
workloads covering clean runs, errored reads, early termination, L2
cost, forced first symbols, and step caps.

Reference: the host loop these paths replace is
/root/reference/src/consensus.rs:258-472 (advance/expand); the run-stop
contract is documented on ``_j_run`` (ops/jax_scorer.py).
"""

import numpy as np
import pytest

from waffle_con_tpu.config import CdwfaConfigBuilder
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.utils.example_gen import generate_test


def _run_once(mode, *, seed, err, et, l2, ms, force=-1, min_count=3,
              wildcard=None, me_budget=2**31 - 1, other_cost=2**31 - 1):
    truth, reads = generate_test(4, 120, 10, err, seed=seed)
    b = (
        CdwfaConfigBuilder()
        .min_count(min_count)
        .allow_early_termination(et)
        .backend("jax")
    )
    if wildcard is not None:
        b = b.wildcard(wildcard)
    sc = JaxScorer(reads, b.build())
    sc._pallas_mode = mode
    h = sc.root(np.ones(len(reads), dtype=bool))
    steps, code, appended, stats, records = sc.run_extend(
        h,
        b"",
        me_budget=me_budget,
        other_cost=other_cost,
        other_len=0,
        min_count=min_count,
        l2=l2,
        max_steps=ms,
        first_sym=force,
    )
    # guard against vacuous off-vs-off comparisons: the interpret run
    # must actually have taken the pallas branch
    took_pallas = sc.counters.get("run_pallas_calls", 0)
    assert (took_pallas >= 1) == (mode == "interpret")
    recs = [(s, f.tolist()) for s, f in records]
    return (
        steps,
        code,
        appended,
        stats.eds.tolist(),
        stats.occ.tolist(),
        stats.split.tolist(),
        stats.reached.tolist(),
        None if stats.fin is None else stats.fin.tolist(),
        recs,
    )


CASES = [
    dict(seed=1, err=0.0, et=False, l2=False, ms=60),
    dict(seed=2, err=0.03, et=False, l2=False, ms=150),
    dict(seed=3, err=0.03, et=True, l2=False, ms=150),
    dict(seed=4, err=0.05, et=True, l2=True, ms=120),
    dict(seed=6, err=0.02, et=False, l2=False, ms=40, force=2),
    dict(seed=7, err=0.0, et=False, l2=False, ms=30, me_budget=20),
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: f"seed{c['seed']}")
def test_pallas_run_parity(case):
    a = _run_once("off", **case)
    b = _run_once("interpret", **case)
    assert a == b


def test_pallas_run_record_absorption():
    """Early-reached reads: the kernel buffers records exactly like the
    XLA path (same (step, fin) pairs, same budget shrinking)."""
    case = dict(seed=11, err=0.0, et=True, l2=False, ms=200)
    a = _run_once("off", **case)
    b = _run_once("interpret", **case)
    assert a == b
    # runs long enough to reach read ends -> records must exist in both
    assert a[1] in (1, 2, 3, 4)


def test_pallas_wildcard_engine_parity():
    """Wildcard reads through the pallas path: exercises the kernel's
    wildcard match (sub == 0) and vote-drop scalar folds."""
    from waffle_con_tpu.models.consensus import ConsensusDWFA

    rng = np.random.default_rng(77)
    truth, reads = generate_test(4, 150, 6, 0.02, seed=78)
    star = ord("*")
    wc_reads = []
    for r in reads:
        arr = bytearray(r)
        for pos in rng.choice(len(arr), size=len(arr) // 15, replace=False):
            arr[pos] = star
        wc_reads.append(bytes(arr))

    def run(mode):
        import waffle_con_tpu.ops.pallas_run as pr

        old = pr.pallas_mode
        pr.pallas_mode = lambda: mode
        try:
            cfg = (
                CdwfaConfigBuilder().min_count(2).wildcard(star)
                .backend("jax").build()
            )
            eng = ConsensusDWFA(cfg)
            for r in wc_reads:
                eng.add_sequence(r)
            return [(c.sequence, c.scores) for c in eng.consensus()]
        finally:
            pr.pallas_mode = old

    assert run("interpret") == run("off")


def test_pallas_priority_engine_parity():
    """Priority chains drive runs at non-zero uniform offsets through
    SubsetScorer views; the pallas path must match the oracle."""
    from waffle_con_tpu.models.priority_consensus import (
        PriorityConsensusDWFA,
    )
    from waffle_con_tpu.native import native_priority_consensus

    t0, lvl0 = generate_test(4, 100, 6, 0.01, seed=31)
    tA = bytes(t0) + b"\x00\x02" * 12
    tB = bytes(t0) + b"\x01\x03" * 12
    chains = [[bytes(r), tA] for r in lvl0[:3]] + [
        [bytes(r), tB] for r in lvl0[3:]
    ]
    mk = lambda be: (  # noqa: E731
        CdwfaConfigBuilder().min_count(2).backend(be).build()
    )
    want = native_priority_consensus(chains, config=mk("native"))

    import waffle_con_tpu.ops.pallas_run as pr

    old = pr.pallas_mode
    pr.pallas_mode = lambda: "interpret"
    try:
        eng = PriorityConsensusDWFA(mk("jax"))
        for ch in chains:
            eng.add_sequence_chain(ch)
        got = eng.consensus()
    finally:
        pr.pallas_mode = old
    flat = lambda p: [  # noqa: E731
        [(c.sequence, c.scores) for c in chain] for chain in p.consensuses
    ]
    assert flat(got) == flat(want)
    assert got.sequence_indices == want.sequence_indices


def test_pallas_engine_e2e_parity():
    """Full consensus() through the engine with the pallas scorer path
    (interpret) matches the native oracle byte-for-byte."""
    from waffle_con_tpu.models.consensus import ConsensusDWFA
    from waffle_con_tpu.native import native_consensus

    truth, reads = generate_test(4, 200, 8, 0.02, seed=21)
    mk = lambda be: (  # noqa: E731
        CdwfaConfigBuilder().min_count(2).backend(be).build()
    )
    want = native_consensus(reads, config=mk("native"))

    import waffle_con_tpu.ops.pallas_run as pr

    old = pr.pallas_mode
    pr.pallas_mode = lambda: "interpret"
    try:
        eng = ConsensusDWFA(config=mk("jax"))
        for r in reads:
            eng.add_sequence(r)
        got = [(c.sequence, c.scores) for c in eng.consensus()]
    finally:
        pr.pallas_mode = old
    assert got == want
