"""Parity of the fused pallas run kernel against the XLA while-loop path.

The pallas kernel (ops/pallas_run.py) re-derives ``_j_run`` as one
Mosaic kernel; these tests run it in interpret mode on the CPU backend
and require decision-for-decision identical results — steps, stop code,
appended symbols, the full stats snapshot, and absorbed records — on
workloads covering clean runs, errored reads, early termination, L2
cost, forced first symbols, and step caps.

Reference: the host loop these paths replace is
/root/reference/src/consensus.rs:258-472 (advance/expand); the run-stop
contract is documented on ``_j_run`` (ops/jax_scorer.py).
"""

import numpy as np
import pytest

from waffle_con_tpu.config import CdwfaConfigBuilder
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.utils.example_gen import generate_test


def _run_once(mode, *, seed, err, et, l2, ms, force=-1, min_count=3,
              wildcard=None, me_budget=2**31 - 1, other_cost=2**31 - 1):
    truth, reads = generate_test(4, 120, 10, err, seed=seed)
    b = (
        CdwfaConfigBuilder()
        .min_count(min_count)
        .allow_early_termination(et)
        .backend("jax")
    )
    if wildcard is not None:
        b = b.wildcard(wildcard)
    sc = JaxScorer(reads, b.build())
    sc._pallas_mode = mode
    h = sc.root(np.ones(len(reads), dtype=bool))
    steps, code, appended, stats, records = sc.run_extend(
        h,
        b"",
        me_budget=me_budget,
        other_cost=other_cost,
        other_len=0,
        min_count=min_count,
        l2=l2,
        max_steps=ms,
        first_sym=force,
    )
    # guard against vacuous off-vs-off comparisons: the interpret run
    # must actually have taken the pallas branch
    took_pallas = sc.counters.get("run_pallas_calls", 0)
    assert (took_pallas >= 1) == (mode == "interpret")
    recs = [(s, f.tolist()) for s, f in records]
    return (
        steps,
        code,
        appended,
        stats.eds.tolist(),
        stats.occ.tolist(),
        stats.split.tolist(),
        stats.reached.tolist(),
        None if stats.fin is None else stats.fin.tolist(),
        recs,
    )


CASES = [
    dict(seed=1, err=0.0, et=False, l2=False, ms=60),
    dict(seed=2, err=0.03, et=False, l2=False, ms=150),
    dict(seed=3, err=0.03, et=True, l2=False, ms=150),
    dict(seed=4, err=0.05, et=True, l2=True, ms=120),
    dict(seed=6, err=0.02, et=False, l2=False, ms=40, force=2),
    dict(seed=7, err=0.0, et=False, l2=False, ms=30, me_budget=20),
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: f"seed{c['seed']}")
def test_pallas_run_parity(case):
    a = _run_once("off", **case)
    b = _run_once("interpret", **case)
    assert a == b


def test_pallas_int32_tile_parity(monkeypatch):
    """The int32 DP-tile variant (the fallback when i16_ok rejects a
    geometry) must stay decision-identical too — every default-config
    test geometry satisfies i16_ok, so force the int32 tile here."""
    monkeypatch.setenv("WAFFLE_PALLAS_I16", "0")
    case = dict(seed=3, err=0.03, et=True, l2=False, ms=150)
    assert _run_once("off", **case) == _run_once("interpret", **case)
    dual = dict(seed=43, err=0.02, et=True, l2=False, weighted=True,
                ms=120)
    assert _dual_once("off", **dual) == _dual_once("interpret", **dual)


def test_pallas_engages_at_north_star_geometry():
    """The VMEM gate must admit the north-star shapes (10 kb reads,
    R=256, E=256): an earlier revision sized the staging from the
    pow2-padded storage axis and silently rejected the fused kernel at
    exactly the scale it was built for."""
    from waffle_con_tpu.ops.pallas_run import (
        fits_budget, i16_ok, staging_rows,
    )

    W = 2 * 256 + 2
    rows = staging_rows(10_050, W)
    assert fits_budget(rows, 256, W, 16_384, sides=1)
    assert i16_ok(16_384, 16_384, W)
    # and a real scorer at a long-read geometry reports eligibility
    rng = np.random.default_rng(5)
    reads = [bytes(rng.integers(0, 4, size=10_050).astype(np.uint8))
             for _ in range(4)]
    sc = JaxScorer(
        reads,
        CdwfaConfigBuilder().min_count(2).backend("jax")
        .initial_band(216).build(),
    )
    sc._pallas_mode = "interpret"
    assert sc._pallas_ok(sides=1)


def test_pallas_band_growth_parity():
    """A deliberately tiny initial band forces code-5 stops + band
    growth mid-search; the pallas path must re-stage (new W geometry)
    and still match the oracle byte-for-byte."""
    from waffle_con_tpu.models.consensus import ConsensusDWFA
    from waffle_con_tpu.native import native_consensus

    truth, reads = generate_test(4, 180, 8, 0.04, seed=91)
    mk = lambda be: (  # noqa: E731
        CdwfaConfigBuilder().min_count(2).backend(be).initial_band(2)
        .build()
    )
    want = native_consensus(reads, config=mk("native"))

    import waffle_con_tpu.ops.pallas_run as pr

    old = pr.pallas_mode
    pr.pallas_mode = lambda: "interpret"
    try:
        eng = ConsensusDWFA(mk("jax"))
        for r in reads:
            eng.add_sequence(r)
        got = [(c.sequence, c.scores) for c in eng.consensus()]
        counters = eng.last_search_stats["scorer_counters"]
    finally:
        pr.pallas_mode = old
    assert got == want
    assert counters.get("grow_e_events", 0) >= 1
    assert counters.get("run_pallas_calls", 0) >= 1


def test_pallas_run_record_absorption():
    """Early-reached reads: the kernel buffers records exactly like the
    XLA path (same (step, fin) pairs, same budget shrinking)."""
    case = dict(seed=11, err=0.0, et=True, l2=False, ms=200)
    a = _run_once("off", **case)
    b = _run_once("interpret", **case)
    assert a == b
    # runs long enough to reach read ends -> records must exist in both
    assert a[1] in (1, 2, 3, 4)


def _dual_once(mode, *, seed, err, et, l2, weighted, ms, delta=5,
               imb=2, lock1=False, lock2=False, min_count=3,
               snps=((40, 1), (90, 2))):
    rng = np.random.default_rng(seed)
    t1, reads1 = generate_test(4, 140, 6, err, seed=seed)
    t2 = bytearray(t1)
    for pos, shift in snps:
        t2[pos] = (t2[pos] + shift) % 4
    from waffle_con_tpu.utils.example_gen import corrupt

    reads2 = [corrupt(bytes(t2), err, rng) for _ in range(6)]
    reads = list(reads1) + reads2
    cfg = (
        CdwfaConfigBuilder()
        .min_count(min_count)
        .allow_early_termination(et)
        .backend("jax")
        .build()
    )
    sc = JaxScorer(reads, cfg)
    sc._pallas_mode = mode
    ha = sc.root(np.ones(len(reads), dtype=bool))
    hb = sc.root(np.ones(len(reads), dtype=bool))
    out = sc.run_extend_dual(
        ha, hb, b"", b"",
        me_budget=2**31 - 1, other_cost=2**31 - 1, other_len=0,
        min_count=min_count, ed_delta=delta, imb_min=imb, l2=l2,
        weighted=weighted, max_steps=ms, lock1=lock1, lock2=lock2,
    )
    (steps, code, app1, app2, st1, st2, act1, act2, records) = out
    took = sc.counters.get("run_dual_pallas_calls", 0)
    assert (took >= 1) == (mode == "interpret")
    recs = [
        (s, f1.tolist(), f2.tolist(), a1.tolist(), a2.tolist())
        for s, f1, f2, a1, a2 in records
    ]
    dump = lambda st: (  # noqa: E731
        st.eds.tolist(), st.occ.tolist(), st.split.tolist(),
        st.reached.tolist(),
    )
    return (steps, code, app1, app2, dump(st1), dump(st2),
            act1.tolist(), act2.tolist(), recs)


DUAL_CASES = [
    dict(seed=41, err=0.0, et=False, l2=False, weighted=False, ms=120),
    dict(seed=42, err=0.02, et=False, l2=False, weighted=False, ms=120),
    dict(seed=43, err=0.02, et=True, l2=False, weighted=True, ms=120),
    dict(seed=44, err=0.03, et=False, l2=True, weighted=False, ms=100,
         delta=2),
    dict(seed=45, err=0.0, et=True, l2=False, weighted=False, ms=160),
]


@pytest.mark.parametrize("case", DUAL_CASES, ids=lambda c: f"seed{c['seed']}")
def test_pallas_dual_run_parity(case):
    assert _dual_once("off", **case) == _dual_once("interpret", **case)


def test_pallas_dual_engine_parity():
    """Full dual consensus through the pallas kernels matches the
    native oracle on a 2-SNP haplotype split."""
    from waffle_con_tpu.models.dual_consensus import DualConsensusDWFA
    from waffle_con_tpu.native import native_dual_consensus
    from waffle_con_tpu.utils.example_gen import corrupt

    t1, reads1 = generate_test(4, 160, 8, 0.01, seed=51)
    t2 = bytearray(t1)
    t2[40] = (t2[40] + 1) % 4
    t2[120] = (t2[120] + 2) % 4
    rng = np.random.default_rng(52)
    reads = list(reads1) + [
        corrupt(bytes(t2), 0.01, rng) for _ in range(8)
    ]
    mk = lambda be: (  # noqa: E731
        CdwfaConfigBuilder().min_count(2).backend(be).build()
    )
    want = native_dual_consensus(reads, config=mk("native"))

    import waffle_con_tpu.ops.pallas_run as pr

    old = pr.pallas_mode
    pr.pallas_mode = lambda: "interpret"
    try:
        eng = DualConsensusDWFA(mk("jax"))
        for r in reads:
            eng.add_sequence(r)
        got = eng.consensus()
    finally:
        pr.pallas_mode = old
    key = lambda res: [  # noqa: E731
        (
            d.consensus1.sequence,
            None if d.consensus2 is None else d.consensus2.sequence,
            d.is_consensus1,
        )
        for d in res
    ]
    assert key(got) == key(want)


def test_pallas_wildcard_engine_parity():
    """Wildcard reads through the pallas path: exercises the kernel's
    wildcard match (sub == 0) and vote-drop scalar folds."""
    from waffle_con_tpu.models.consensus import ConsensusDWFA

    rng = np.random.default_rng(77)
    truth, reads = generate_test(4, 150, 6, 0.02, seed=78)
    star = ord("*")
    wc_reads = []
    for r in reads:
        arr = bytearray(r)
        for pos in rng.choice(len(arr), size=len(arr) // 15, replace=False):
            arr[pos] = star
        wc_reads.append(bytes(arr))

    def run(mode):
        import waffle_con_tpu.ops.pallas_run as pr

        old = pr.pallas_mode
        pr.pallas_mode = lambda: mode
        try:
            cfg = (
                CdwfaConfigBuilder().min_count(2).wildcard(star)
                .backend("jax").build()
            )
            eng = ConsensusDWFA(cfg)
            for r in wc_reads:
                eng.add_sequence(r)
            return [(c.sequence, c.scores) for c in eng.consensus()]
        finally:
            pr.pallas_mode = old

    assert run("interpret") == run("off")


def test_pallas_priority_engine_parity():
    """Priority chains drive runs at non-zero uniform offsets through
    SubsetScorer views; the pallas path must match the oracle."""
    from waffle_con_tpu.models.priority_consensus import (
        PriorityConsensusDWFA,
    )
    from waffle_con_tpu.native import native_priority_consensus

    t0, lvl0 = generate_test(4, 100, 6, 0.01, seed=31)
    tA = bytes(t0) + b"\x00\x02" * 12
    tB = bytes(t0) + b"\x01\x03" * 12
    chains = [[bytes(r), tA] for r in lvl0[:3]] + [
        [bytes(r), tB] for r in lvl0[3:]
    ]
    mk = lambda be: (  # noqa: E731
        CdwfaConfigBuilder().min_count(2).backend(be).build()
    )
    want = native_priority_consensus(chains, config=mk("native"))

    import waffle_con_tpu.ops.pallas_run as pr

    old = pr.pallas_mode
    pr.pallas_mode = lambda: "interpret"
    try:
        eng = PriorityConsensusDWFA(mk("jax"))
        for ch in chains:
            eng.add_sequence_chain(ch)
        got = eng.consensus()
        counters = eng.last_search_stats["scorer_counters"]
    finally:
        pr.pallas_mode = old
    # the chains' uniform nonzero-offset runs must take the fused path
    assert counters.get("run_pallas_calls", 0) >= 1
    flat = lambda p: [  # noqa: E731
        [(c.sequence, c.scores) for c in chain] for chain in p.consensuses
    ]
    assert flat(got) == flat(want)
    assert got.sequence_indices == want.sequence_indices


def test_pallas_engine_e2e_parity():
    """Full consensus() through the engine with the pallas scorer path
    (interpret) matches the native oracle byte-for-byte."""
    from waffle_con_tpu.models.consensus import ConsensusDWFA
    from waffle_con_tpu.native import native_consensus

    truth, reads = generate_test(4, 200, 8, 0.02, seed=21)
    mk = lambda be: (  # noqa: E731
        CdwfaConfigBuilder().min_count(2).backend(be).build()
    )
    want = native_consensus(reads, config=mk("native"))

    import waffle_con_tpu.ops.pallas_run as pr

    old = pr.pallas_mode
    pr.pallas_mode = lambda: "interpret"
    try:
        eng = ConsensusDWFA(config=mk("jax"))
        for r in reads:
            eng.add_sequence(r)
        got = [(c.sequence, c.scores) for c in eng.consensus()]
    finally:
        pr.pallas_mode = old
    assert got == want
