"""Lint-engine contract tests: each rule against positive/negative
fixture snippets, the disable-comment escape hatch, the doc-sync check,
and the gate the CI step relies on — a full-tree run with all five
rules active reporting zero violations inside the 10 s budget.
"""

import time
from pathlib import Path

import pytest

from waffle_con_tpu.analysis import lint
from waffle_con_tpu.utils import envspec

REPO = Path(__file__).resolve().parent.parent


def rules_of(violations):
    return [v.rule for v in violations]


# ---------------------------------------------------------------------
# WL001 env-registry


def test_wl001_flags_direct_reads():
    src = (
        'import os\n'
        'a = os.environ.get("WAFFLE_METRICS")\n'
        'b = os.getenv("WAFFLE_TRACE", "")\n'
        'c = os.environ["WAFFLE_PROFILE"]\n'
        'd = "WAFFLE_FAULTS" in os.environ\n'
    )
    v = lint.lint_source(src, "waffle_con_tpu/obs/x.py", rules=["WL001"])
    assert rules_of(v) == ["WL001"] * 4
    assert [x.line for x in v] == [2, 3, 4, 5]


def test_wl001_allows_writes_registry_and_foreign_keys():
    src = (
        'import os\n'
        'os.environ.setdefault("WAFFLE_RUN_COLS", "1")\n'
        'os.environ["WAFFLE_RAGGED"] = "0"\n'
        'os.environ.pop("WAFFLE_FAULTS", None)\n'
        'x = os.environ.get("JAX_PLATFORMS")\n'
        'from waffle_con_tpu.utils import envspec\n'
        'y = envspec.get_raw("WAFFLE_TRACE")\n'
    )
    assert lint.lint_source(src, "waffle_con_tpu/obs/x.py",
                            rules=["WL001"]) == []


def test_wl001_envspec_itself_exempt():
    src = 'import os\nx = os.environ.get("WAFFLE_TRACE")\n'
    assert lint.lint_source(src, "waffle_con_tpu/utils/envspec.py",
                            rules=["WL001"]) == []


def test_wl001_doc_sync_both_directions():
    v = lint.check_env_docs("mentions WAFFLE_TRACE only",
                            ["WAFFLE_TRACE", "WAFFLE_METRICS"])
    assert len(v) == 1 and "WAFFLE_METRICS" in v[0].message
    v = lint.check_env_docs("WAFFLE_TRACE and WAFFLE_GHOST",
                            ["WAFFLE_TRACE"])
    assert len(v) == 1 and "WAFFLE_GHOST" in v[0].message
    assert lint.check_env_docs("WAFFLE_TRACE", ["WAFFLE_TRACE"]) == []


# ---------------------------------------------------------------------
# WL002 sync-at-seam


def test_wl002_flags_unsanctioned_sync():
    src = (
        'import jax\n'
        'def pop_loop(dev):\n'
        '    out = jax.device_get(dev)\n'
        '    jax.block_until_ready(dev)\n'
        '    n = dev.item()\n'
    )
    v = lint.lint_source(src, "waffle_con_tpu/models/engine.py",
                        rules=["WL002"])
    assert rules_of(v) == ["WL002"] * 3


def test_wl002_sanctioned_scopes_and_out_of_scope_files():
    src = (
        'import jax\n'
        'def pop_loop(dev, rec):\n'
        '    with _phases.transfer_scope(rec):\n'
        '        out = jax.device_get(dev)\n'
        '    with _phases.device_scope(rec):\n'
        '        jax.block_until_ready(dev)\n'
        'class DeferredStats:\n'
        '    def resolve(self, dev):\n'
        '        return jax.device_get(dev)\n'
    )
    assert lint.lint_source(src, "waffle_con_tpu/ops/ragged.py",
                            rules=["WL002"]) == []
    # same sync calls, but the file is outside the rule's scope
    bare = 'import jax\nx = jax.device_get(1)\n'
    assert lint.lint_source(bare, "waffle_con_tpu/ops/jax_scorer.py",
                            rules=["WL002"]) == []
    assert len(lint.lint_source(bare, "waffle_con_tpu/models/m.py",
                                rules=["WL002"])) == 1


# ---------------------------------------------------------------------
# WL003 mutation-hook completeness


WL003_PATH = "waffle_con_tpu/ops/jax_scorer.py"


def test_wl003_flags_unhooked_writer():
    src = (
        'class JaxScorer:\n'
        '    def free(self, h):\n'
        '        self._state[h] = None\n'
    )
    v = lint.lint_source(src, WL003_PATH, rules=["WL003"])
    assert rules_of(v) == ["WL003"]
    assert v[0].line == 2  # anchored at the def line


def test_wl003_hooked_writer_init_and_other_classes_clean():
    src = (
        'class JaxScorer:\n'
        '    def __init__(self):\n'
        '        self._state = []\n'
        '    def free(self, h):\n'
        '        self._state[h] = None\n'
        '        self._spec_drop(h)\n'
        '    def stats(self, h):\n'
        '        return self._state[h]\n'
        'class Other:\n'
        '    def free(self, h):\n'
        '        self._state[h] = None\n'
    )
    assert lint.lint_source(src, WL003_PATH, rules=["WL003"]) == []


def test_wl003_def_line_disable_covers_method():
    src = (
        'class JaxScorer:\n'
        '    def root(self):  # waffle-lint: disable=WL003(fresh slot)\n'
        '        self._off_host[0] = 1\n'
    )
    assert lint.lint_source(src, WL003_PATH, rules=["WL003"]) == []


# ---------------------------------------------------------------------
# WL004 traced-purity


def test_wl004_flags_impurity_in_traced_bodies():
    src = (
        'import time, jax\n'
        '@jax.jit\n'
        'def step(x):\n'
        '    t = time.perf_counter()\n'
        '    print(x)\n'
        '    return x\n'
        'def body(c):\n'
        '    return random.random()\n'
        'def run(c):\n'
        '    return lax.while_loop(lambda c: True, body, c)\n'
    )
    v = lint.lint_source(src, "waffle_con_tpu/ops/kern.py",
                        rules=["WL004"])
    msgs = " ".join(x.message for x in v)
    assert rules_of(v) == ["WL004"] * 3
    assert "time.perf_counter" in msgs and "print" in msgs \
        and "random.random" in msgs


def test_wl004_untraced_and_out_of_scope_clean():
    src = (
        'import time\n'
        'def host_side(x):\n'
        '    return time.perf_counter()\n'
    )
    assert lint.lint_source(src, "waffle_con_tpu/ops/kern.py",
                            rules=["WL004"]) == []
    traced = (
        'import time, jax\n'
        '@jax.jit\n'
        'def step(x):\n'
        '    return time.time()\n'
    )
    assert lint.lint_source(traced, "waffle_con_tpu/serve/s.py",
                            rules=["WL004"]) == []


# ---------------------------------------------------------------------
# WL005 bare-thread/bare-lock


def test_wl005_flags_bare_primitives():
    src = (
        'import threading\n'
        'from threading import RLock\n'
        'a = threading.Lock()\n'
        'b = RLock()\n'
        'c = threading.Thread(target=print)\n'
    )
    v = lint.lint_source(src, "waffle_con_tpu/serve/x.py",
                        rules=["WL005"])
    assert rules_of(v) == ["WL005"] * 3


def test_wl005_wrappers_and_lockcheck_itself_clean():
    src = (
        'from waffle_con_tpu.analysis import lockcheck\n'
        'a = lockcheck.make_lock("serve.x")\n'
        'b = lockcheck.make_rlock("serve.y")\n'
        't = lockcheck.make_thread(target=print)\n'
        'cond = threading.Condition()\n'  # not a covered primitive
    )
    assert lint.lint_source(src, "waffle_con_tpu/serve/x.py",
                            rules=["WL005"]) == []
    bare = 'import threading\nmu = threading.Lock()\n'
    assert lint.lint_source(
        bare, "waffle_con_tpu/analysis/lockcheck.py", rules=["WL005"]
    ) == []


# ---------------------------------------------------------------------
# disable-comment mechanics


def test_disable_requires_reason_and_matching_rule():
    flagged = 'import threading\nmu = threading.Lock()  # waffle-lint: disable=WL005()\n'
    assert len(lint.lint_source(flagged, "waffle_con_tpu/a.py",
                                rules=["WL005"])) == 1
    wrong_rule = 'import threading\nmu = threading.Lock()  # waffle-lint: disable=WL001(reason)\n'
    assert len(lint.lint_source(wrong_rule, "waffle_con_tpu/a.py",
                                rules=["WL005"])) == 1
    ok = 'import threading\nmu = threading.Lock()  # waffle-lint: disable=WL005(graph mutex)\n'
    assert lint.lint_source(ok, "waffle_con_tpu/a.py",
                            rules=["WL005"]) == []


def test_disable_multiple_rules_on_one_line():
    src = (
        'import os, threading\n'
        'x = os.environ.get("WAFFLE_TRACE") or threading.Lock()'
        '  # waffle-lint: disable=WL001(fixture),WL005(fixture)\n'
    )
    assert lint.lint_source(src, "waffle_con_tpu/a.py") == []


def test_syntax_error_reported_not_raised():
    v = lint.lint_source("def broken(:\n", "waffle_con_tpu/a.py")
    assert rules_of(v) == ["WL000"]


# ---------------------------------------------------------------------
# the full-tree gate


def test_full_tree_zero_violations_within_budget():
    started = time.monotonic()
    violations = lint.lint_tree(REPO)
    violations += lint.check_env_docs(
        (REPO / "README.md").read_text(), envspec.KNOBS, "README.md"
    )
    elapsed = time.monotonic() - started
    assert violations == [], "\n".join(v.render() for v in violations)
    assert elapsed < 10.0, f"lint took {elapsed:.1f}s, budget is 10s"


def test_tree_scan_covers_the_expected_roots():
    files = {str(p.relative_to(REPO)) for p in lint.iter_python_files(REPO)}
    assert "bench.py" in files and "conftest.py" in files
    assert "waffle_con_tpu/ops/jax_scorer.py" in files
    assert "scripts/waffle_lint.py" in files
    assert not any(f.startswith("tests/") for f in files)


def test_env_table_lists_every_knob():
    table = envspec.env_table_markdown()
    for knob in envspec.knobs():
        assert f"`{knob.name}`" in table


def test_envspec_rejects_unregistered_names():
    with pytest.raises(KeyError):
        envspec.get_raw("WAFFLE_NOT_A_KNOB")
    with pytest.raises(KeyError):
        envspec.flag("WAFFLE_NOT_A_KNOB")


def test_envspec_typed_getters(monkeypatch):
    monkeypatch.setenv("WAFFLE_RAGGED_ROWS", "7")
    assert envspec.get_int("WAFFLE_RAGGED_ROWS", 256, 16, 65536) == 16
    monkeypatch.setenv("WAFFLE_RAGGED_ROWS", "garbage")
    assert envspec.get_int("WAFFLE_RAGGED_ROWS", 256, 16, 65536) == 256
    monkeypatch.setenv("WAFFLE_SLO_K", "2.5")
    assert envspec.get_float("WAFFLE_SLO_K", 3.0) == 2.5
    monkeypatch.setenv("WAFFLE_METRICS", "0")
    assert not envspec.flag("WAFFLE_METRICS")
    monkeypatch.setenv("WAFFLE_METRICS", "1")
    assert envspec.flag("WAFFLE_METRICS")
