"""Golden-fixture parity for the complete C++ dual and priority engines
(the reference-speed CPU baselines): every result object must equal the
Python engine's output, including score vectors."""

import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusCost,
    DualConsensusDWFA,
    PriorityConsensusDWFA,
)
from waffle_con_tpu.native import (
    native_dual_consensus,
    native_priority_consensus,
)
from waffle_con_tpu.utils.fixtures import (
    load_dual_fixture,
    load_priority_fixture,
)


def dual_config(**kw):
    b = CdwfaConfigBuilder().wildcard(ord("*"))
    for k, v in kw.items():
        getattr(b, k)(v)
    return b.build()


def run_dual_fixture(name, include_consensus, config=None):
    if config is None:
        config = dual_config()
    sequences, expected = load_dual_fixture(
        name, include_consensus, config.consensus_cost
    )
    engine = DualConsensusDWFA(config)
    for s in sequences:
        engine.add_sequence(s)
    want = engine.consensus()
    got = native_dual_consensus(sequences, config=config)
    assert got == want
    assert [expected] == got
    for a, b in zip(got, want):
        assert a.scores1 == b.scores1
        assert a.scores2 == b.scores2
        assert a.consensus1.scores == b.consensus1.scores
        if a.consensus2 is not None:
            assert a.consensus2.scores == b.consensus2.scores


def run_priority_fixture(name, include_consensus, config=None):
    if config is None:
        config = dual_config()
    chains, expected = load_priority_fixture(
        name, include_consensus, config.consensus_cost
    )
    engine = PriorityConsensusDWFA(config)
    for chain in chains:
        engine.add_sequence_chain(chain)
    want = engine.consensus()
    got = native_priority_consensus(chains, config=config)
    assert got.sequence_indices == want.sequence_indices
    assert got.sequence_indices == expected.sequence_indices
    assert len(got.consensuses) == len(want.consensuses)
    for got_chain, want_chain in zip(got.consensuses, want.consensuses):
        assert len(got_chain) == len(want_chain)
        for g, w in zip(got_chain, want_chain):
            assert g.sequence == w.sequence
            assert g.scores == w.scores


def test_dual_001():
    run_dual_fixture("dual_001", True)


def test_dual_length_gap_l2_offsets():
    """length_gap_001: L2 cost + late-activating offset reads."""
    config = dual_config(consensus_cost=ConsensusCost.L2_DISTANCE)
    sequences, expected = load_dual_fixture(
        "length_gap_001", True, config.consensus_cost
    )
    # reference runner feeds offsets: reads that are suffix-aligned start
    # late; mirror the python-engine test by letting auto-shift handle it
    engine = DualConsensusDWFA(config)
    for s in sequences:
        engine.add_sequence(s)
    want = engine.consensus()
    got = native_dual_consensus(sequences, config=config)
    assert got == want


def test_dual_early_termination():
    run_dual_fixture(
        "dual_early_termination_001",
        True,
        dual_config(allow_early_termination=True, min_count=2),
    )


def test_priority_001():
    run_priority_fixture("priority_001", True)


def test_priority_002():
    run_priority_fixture("priority_002", True)


def test_priority_003():
    run_priority_fixture("priority_003", True)


def test_multi_exact_001():
    run_priority_fixture("multi_exact_001", True)


def test_multi_exact_002():
    run_priority_fixture("multi_exact_002", True)


def test_multi_err_001():
    run_priority_fixture("multi_err_001", False)


def test_multi_err_002():
    run_priority_fixture("multi_err_002", False)


def test_multi_samesplit():
    run_priority_fixture("multi_samesplit_001", True)


def test_multi_postcon():
    run_priority_fixture("multi_postcon_001", True, dual_config(min_count=2))


def test_dual_weighted_by_ed():
    """weighted_by_ed vote scaling through both engines."""
    seqs = [b"ACGTACGTACGT"] * 4 + [b"ACCTACGTACGT"] * 4
    config = (
        CdwfaConfigBuilder().min_count(2).weighted_by_ed(True).build()
    )
    engine = DualConsensusDWFA(config)
    for s in seqs:
        engine.add_sequence(s)
    want = engine.consensus()
    got = native_dual_consensus(seqs, config=config)
    assert got == want


def test_dual_min_af_dynamic_counts():
    seqs = [b"ACGTACGTACGT"] * 6 + [b"ACCTACGTACGT"] * 2
    config = CdwfaConfigBuilder().min_count(1).min_af(0.3).build()
    engine = DualConsensusDWFA(config)
    for s in seqs:
        engine.add_sequence(s)
    want = engine.consensus()
    got = native_dual_consensus(seqs, config=config)
    assert got == want


def test_dual_empty_fallback():
    """Gap between reads: the dual engine returns the empty-consensus
    fallback rather than erroring."""
    config = CdwfaConfigBuilder().min_count(3).build()
    seqs = [b"AAAA", b"CCCC", b"GGGG"]
    engine = DualConsensusDWFA(config)
    for s in seqs:
        engine.add_sequence(s)
    want = engine.consensus()
    got = native_dual_consensus(seqs, config=config)
    assert got == want
    assert got[0].consensus1.sequence in (b"", want[0].consensus1.sequence)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_dual_randomized_parity(seed):
    """Randomized two-haplotype instances: native == python exactly."""
    from waffle_con_tpu.utils.example_gen import generate_test

    truth, reads = generate_test(4, 80, 6, 0.02, seed=seed)
    h2 = bytearray(truth)
    h2[len(h2) // 2] = (h2[len(h2) // 2] + 1) % 4
    _truth2, reads2 = generate_test(4, 80, 6, 0.02, seed=seed + 100)
    reads = list(reads) + [bytes(h2)] * 4

    config = CdwfaConfigBuilder().min_count(2).build()
    engine = DualConsensusDWFA(config)
    for s in reads:
        engine.add_sequence(s)
    want = engine.consensus()
    got = native_dual_consensus(reads, config=config)
    assert got == want
    for a, b in zip(got, want):
        assert a.scores1 == b.scores1
        assert a.scores2 == b.scores2
