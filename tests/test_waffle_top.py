"""``scripts/waffle_top.py`` rendering: the top-style dashboard must
render a service stats payload (the ``WAFFLE_STATS_FILE`` JSON the
serve layer publishes) without a live service — pure fixture in,
panel text out — and the CLI ``--once`` path must round-trip a file.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "waffle_top.py",
)


def _load_waffle_top():
    spec = importlib.util.spec_from_file_location("waffle_top", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def waffle_top():
    return _load_waffle_top()


def _payload():
    """A representative stats file: the shape serve/service.py writes
    (jobs + dispatch occupancy + SLO windows + metrics + incidents)."""
    return {
        "unix_time": 1700000000.0,
        "service": "waffle-serve",
        "stats": {
            "jobs": {
                "submitted": 12, "done": 9, "failed": 1,
                "expired": 0, "cancelled": 0, "rejected": 2,
            },
            "queue_depth": 3,
            "dispatch": {
                "batches": 40, "coalesced_batches": 25,
                "direct_dispatches": 15,
                "mean_batch_occupancy": 2.75, "occupancy_max": 6,
            },
        },
        "slo": {
            "k": 4.0,
            "slow_searches": 1,
            "dispatch": {
                "count": 200, "p50_s": 0.004, "p95_s": 0.02,
                "p99_s": 0.05, "ewma_s": 0.006,
            },
            "job": {
                "count": 9, "p50_s": 0.8, "p95_s": 2.5,
                "p99_s": 3.0, "ewma_s": 1.1,
            },
        },
        "metrics": {
            "waffle_dispatch_latency_seconds": {
                "series": {
                    'backend="jax",op="run"': {
                        "count": 150, "sum": 1.5,
                    },
                    'backend="jax",op="stats"': {
                        "count": 50, "sum": 0.1,
                    },
                },
            },
        },
        "incidents": [
            {
                "unix_time": 1699999990.0,
                "reason": "backend_demoted",
                "trace_id": "job-7",
                "path": None,
            },
        ],
    }


def _fleet_payload():
    """A ProcFrontDoor payload with the fleet observability plane on:
    per-worker STATS bookkeeping + the door-level ``fleet`` rollup."""
    payload = _payload()
    payload["service"] = "storm"
    payload["fleet"] = {
        "stats_frames": 14,
        "incidents_forwarded": 3,
        "span_events": 220,
    }
    payload["workers"] = [
        {
            "worker": "storm:w0", "pid": 4242, "state": "up",
            "outstanding": 1, "slots": 4, "occupancy": 0.25,
            "routed": 6, "requeues": 0, "migrations": 0, "restarts": 0,
            "ckpt_frames": 2, "ckpt_bytes": 4096, "demotions": 0,
            "sheds": 0, "readmits": 0,
            "stats_frames": 8, "stats_at": 1699999998.0,
            "incidents": 3, "span_events": 120,
            "dispatch_p95_s": 0.018,
        },
        {
            "worker": "storm:w1", "pid": 4243, "state": "up",
            "outstanding": 0, "slots": 4, "occupancy": 0.0,
            "routed": 6, "requeues": 0, "migrations": 0, "restarts": 0,
            "ckpt_frames": 0, "ckpt_bytes": 0, "demotions": 0,
            "sheds": 0, "readmits": 0,
            "stats_frames": 6, "stats_at": None,
            "incidents": 0, "span_events": 100,
            "dispatch_p95_s": None,
        },
    ]
    return payload


def test_render_panels_from_fixture(waffle_top):
    out = waffle_top.render(_payload(), plain=True)
    assert "\x1b[" not in out  # plain mode: no ANSI escapes
    assert "service 'waffle-serve'" in out
    assert "submitted=12" in out and "done=9" in out
    assert "rejected=2" in out and "queue_depth=3" in out
    assert "coalesced=25" in out and "mean_occupancy=2.75" in out
    assert "slow_searches=1" in out
    assert "p95=20.0ms" in out  # dispatch window
    assert "p95=2.50s" in out  # job window
    assert 'backend="jax",op="run"' in out
    assert "mean=10.0ms" in out  # 1.5s / 150
    assert "backend_demoted" in out and "trace=job-7" in out
    assert "(in-memory)" in out


def test_render_minimal_payload_does_not_crash(waffle_top):
    out = waffle_top.render({}, plain=True)
    assert "waffle_top" in out
    assert "recent incidents (0)" in out
    assert "none" in out


def test_render_styled_mode_uses_ansi(waffle_top):
    assert "\x1b[1m" in waffle_top.render(_payload(), plain=False)


def test_cli_once_round_trips_stats_file(tmp_path):
    stats = tmp_path / "stats.json"
    stats.write_text(json.dumps(_payload()))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(stats), "--once"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "waffle_top" in proc.stdout
    assert "submitted=12" in proc.stdout


def test_cli_once_missing_file_exits_nonzero(tmp_path):
    proc = subprocess.run(
        [sys.executable, _SCRIPT, str(tmp_path / "absent.json"),
         "--once"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    assert "waiting for" in proc.stdout


def test_cli_env_var_supplies_stats_file(tmp_path):
    stats = tmp_path / "stats.json"
    stats.write_text(json.dumps(_payload()))
    env = dict(os.environ, WAFFLE_STATS_FILE=str(stats))
    proc = subprocess.run(
        [sys.executable, _SCRIPT, "--once"],
        capture_output=True, text=True, timeout=60, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    assert "queue_depth=3" in proc.stdout


def test_render_replica_table(waffle_top):
    payload = _payload()
    payload["service"] = "consensus"
    payload["replicas"] = [
        {
            "replica": "consensus:r0", "state": "up", "outstanding": 2,
            "queue_depth": 1, "routed": 7, "demotions": 0, "sheds": 0,
            "readmits": 0, "jobs": {"done": 5},
            "mean_batch_occupancy": 1.5, "last_hold_ms": 1.2,
        },
        {
            "replica": "consensus:r1", "state": "draining",
            "outstanding": 0, "queue_depth": 0, "routed": 3,
            "demotions": 1, "sheds": 0, "readmits": 0,
            "jobs": {"done": 3}, "mean_batch_occupancy": 1.0,
        },
    ]
    out = waffle_top.render(payload, plain=True)
    assert "replicas (2)" in out
    assert "consensus:r0" in out and "consensus:r1" in out
    assert "draining" in out
    assert "1.2ms" in out


def test_render_worker_process_table(waffle_top):
    payload = _payload()
    payload["service"] = "storm"
    payload["workers"] = [
        {
            "worker": "storm:w0", "pid": 4242, "state": "up",
            "outstanding": 2, "slots": 2, "occupancy": 1.0,
            "routed": 9, "requeues": 0, "demotions": 0, "sheds": 0,
            "readmits": 0,
        },
        {
            "worker": "storm:w1", "pid": None, "state": "lost",
            "outstanding": 0, "slots": 2, "occupancy": 0.0,
            "routed": 4, "requeues": 3, "demotions": 1, "sheds": 0,
            "readmits": 0,
        },
    ]
    out = waffle_top.render(payload, plain=True)
    assert "worker processes (2)" in out
    assert "storm:w0" in out and "4242" in out
    assert "storm:w1" in out and "lost" in out
    # a dead worker renders a placeholder pid, not a crash
    lost_row = next(l for l in out.splitlines() if "storm:w1" in l)
    assert " - " in lost_row
    assert "1.00" in out  # occupancy column


def test_render_fleet_section(waffle_top):
    out = waffle_top.render(_fleet_payload(), plain=True)
    # fleet rollup line: forwarded-frame counters + door-side e2e SLO
    assert "fleet" in out
    assert "stats_frames=14" in out
    assert "incidents_forwarded=3" in out
    assert "span_events=220" in out
    assert "e2e p50=800.0ms p95=2.50s" in out
    # per-worker plane table: snapshot age from the last STATS frame
    # (unix_time 1700000000 - stats_at 1699999998 = 2.0s), the
    # worker's own rolling dispatch p95, and "-" placeholders for a
    # worker that has not shipped a STATS frame yet
    lines = out.splitlines()
    fleet_idx = next(
        i for i, l in enumerate(lines) if l.startswith("fleet ")
    )
    w0_row = next(
        l for l in lines[fleet_idx:] if l.lstrip().startswith("storm:w0")
    )
    w1_row = next(
        l for l in lines[fleet_idx:] if l.lstrip().startswith("storm:w1")
    )
    assert "2.0s" in w0_row and "18.0ms" in w0_row and "120" in w0_row
    assert w1_row.split() == ["storm:w1", "-", "6", "0", "100", "-"]


def test_render_cache_panel(waffle_top):
    payload = _payload()
    payload["stats"]["cache"] = {
        "exact": 5, "certified": 2, "checkpoint": 1, "misses": 4,
        "deposits": 4, "ckpt_deposits": 3, "certify_failed": 1,
        "results": 4, "checkpoints": 3, "quarantined": 1,
    }
    out = waffle_top.render(payload, plain=True)
    assert "cache: hits=8" in out
    assert "exact=5" in out and "certified=2" in out and "ckpt=1" in out
    assert "misses=4" in out and "quarantined=1" in out
    assert "store=4r/3c" in out


def test_render_cache_panel_absent_without_cache_stats(waffle_top):
    # a cache-off payload (no "cache" in stats) renders no cache line
    out = waffle_top.render(_payload(), plain=True)
    assert "cache:" not in out


def test_render_audit_panel(waffle_top):
    payload = _payload()
    payload["audit"] = {
        "records": 123, "shadow_pops": 45, "divergences": 0,
        "enabled": True, "shadow": "python",
    }
    out = waffle_top.render(payload, plain=True)
    assert "audit: records=123" in out
    assert "shadow=python" in out
    assert "shadow_pops=45" in out and "divergences=0" in out


def test_render_audit_panel_shadow_off(waffle_top):
    payload = _payload()
    payload["audit"] = {
        "records": 7, "shadow_pops": 0, "divergences": 0,
        "enabled": True, "shadow": None,
    }
    out = waffle_top.render(payload, plain=True)
    assert "audit: records=7" in out and "shadow=off" in out


def test_render_audit_panel_absent_without_audit_field(waffle_top):
    # audit plane off -> the service publishes no "audit" key -> no line
    out = waffle_top.render(_payload(), plain=True)
    assert "audit:" not in out


def test_render_fleet_section_absent_without_fleet_field(waffle_top):
    # a pre-fleet door payload (workers but no "fleet") must render the
    # worker table only — no fleet rollup, no crash
    payload = _fleet_payload()
    del payload["fleet"]
    out = waffle_top.render(payload, plain=True)
    assert "worker processes (2)" in out
    assert "stats_frames=" not in out
    assert "incidents_forwarded=" not in out
