"""Replicated front-door contract tests (python-backend, device-free).

Core claims: N in-process replicas behind :class:`ReplicatedService`
return results byte-identical to serial execution while the door
spreads load by least-outstanding work; flight-trigger health signals
(``backend_demoted`` / ``slow_search``) drain or deprioritize exactly
the replica they're attributed to (by trace-id prefix); drained
replicas re-admit once their outstanding work reaches zero; and the
front door owns the ``WAFFLE_STATS_FILE`` payload with a per-replica
table.  Jobs here run the python backend so the tests are fast and
jax-free — routing and health logic are backend-agnostic.
"""

import json

import pytest

from waffle_con_tpu import CdwfaConfigBuilder
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.serve import (
    JobRequest,
    ReplicatedConfig,
    ReplicatedService,
    ServeConfig,
)
from waffle_con_tpu.serve import replicas as serve_replicas
from waffle_con_tpu.serve.service import _build_engine
from waffle_con_tpu.utils.example_gen import generate_test

pytestmark = pytest.mark.serve


def _cfg(**kw):
    b = CdwfaConfigBuilder().backend("python")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _requests(n, seq_len=160, reads=6):
    cfg = _cfg(min_count=2)
    out = []
    for seed in range(n):
        _, r = generate_test(4, seq_len, reads, 0.02, seed=seed)
        out.append(JobRequest(kind="single", reads=tuple(r), config=cfg))
    return out


def _door(replicas=2, **cfg_kw):
    return ReplicatedService(ReplicatedConfig(
        replicas=replicas,
        base=ServeConfig(workers=2, batch_window_s=0.002),
        **cfg_kw,
    ))


# ------------------------------------------------------ parity + routing


def test_replicated_results_byte_identical_to_serial():
    requests = _requests(6)
    expected = [_build_engine(r).consensus() for r in requests]
    with _door(replicas=2) as door:
        handles = door.submit_all(requests)
        results = [h.result(timeout=120) for h in handles]
        stats = door.stats()
    assert results == expected
    assert stats["jobs"]["done"] == 6
    assert stats["jobs"].get("failed", 0) == 0


def test_least_outstanding_routing_uses_both_replicas():
    requests = _requests(6)
    with _door(replicas=2) as door:
        handles = door.submit_all(requests)
        for h in handles:
            h.result(timeout=120)
        reps = door.replica_stats()
    routed = {r["replica"]: r["routed"] for r in reps}
    assert sum(routed.values()) == 6
    assert all(v >= 1 for v in routed.values()), routed


def test_replica_names_and_trace_prefix():
    with _door(replicas=2) as door:
        handle = door.submit(_requests(1)[0])
        handle.result(timeout=120)
        names = [r["replica"] for r in door.replica_stats()]
    assert names == ["consensus:r0", "consensus:r1"]
    assert any(
        handle.trace.trace_id.startswith(name + "/") for name in names
    ), handle.trace.trace_id


# ---------------------------------------------------- health transitions


def test_backend_demotion_drains_attributed_replica(monkeypatch):
    with _door(replicas=2) as door:
        r0 = door._replicas[0]
        # pin outstanding work so the drain can't re-admit mid-test
        monkeypatch.setattr(r0.service, "outstanding", lambda: 1)
        obs_flight.trigger(
            "backend_demoted", trace_id=f"{r0.name}/job-999",
            from_backend="jax",
        )
        reps = {r["replica"]: r for r in door.replica_stats()}
        assert reps[r0.name]["state"] == serve_replicas.DRAINING
        assert reps[r0.name]["demotions"] == 1
        assert reps["consensus:r1"]["state"] == serve_replicas.UP

        # new admissions reroute around the draining replica
        handles = door.submit_all(_requests(3))
        for h in handles:
            h.result(timeout=120)
        reps = {r["replica"]: r for r in door.replica_stats()}
        assert reps[r0.name]["routed"] == 0
        assert reps["consensus:r1"]["routed"] == 3


def test_drained_replica_readmits_at_zero_outstanding():
    with _door(replicas=2) as door:
        r0 = door._replicas[0]
        obs_flight.trigger(
            "backend_demoted", trace_id=f"{r0.name}/job-998",
            from_backend="jax",
        )
        assert door.replica_stats()[0]["state"] == serve_replicas.DRAINING
        # outstanding is already 0, so the next routing decision
        # re-admits before placing the job
        door.submit(_requests(1)[0]).result(timeout=120)
        rep = door.replica_stats()[0]
        assert rep["state"] == serve_replicas.UP
        assert rep["readmits"] == 1


def test_slow_search_sheds_until_cooldown(monkeypatch):
    with _door(replicas=2, shed_cooldown_s=120.0) as door:
        r0 = door._replicas[0]
        obs_flight.trigger(
            "slow_search", trace_id=f"{r0.name}/job-997", p95_s=9.9,
        )
        assert door.replica_stats()[0]["state"] == serve_replicas.SHEDDING
        # shedding deprioritizes: the job lands on the healthy replica
        # even though r0 has equal outstanding work and a lower index
        door.submit(_requests(1)[0]).result(timeout=120)
        reps = {r["replica"]: r for r in door.replica_stats()}
        assert reps[r0.name]["routed"] == 0
        assert reps[r0.name]["sheds"] == 1
        assert reps["consensus:r1"]["routed"] == 1
        # expired cooldown restores the replica at the next decision
        monkeypatch.setattr(r0, "shed_until", 0.0)
        door.submit(_requests(1)[0]).result(timeout=120)
        assert door.replica_stats()[0]["state"] == serve_replicas.UP


def test_all_unhealthy_falls_back_to_least_outstanding(monkeypatch):
    with _door(replicas=2) as door:
        for i, rep in enumerate(door._replicas):
            monkeypatch.setattr(rep.service, "outstanding", lambda: 0)
            obs_flight.trigger(
                "backend_demoted", trace_id=f"{rep.name}/job-{990 + i}",
                from_backend="jax",
            )
            rep.state = serve_replicas.DRAINING
            monkeypatch.setattr(
                rep.service, "outstanding", (lambda: 1)
            )
        # every replica unhealthy: degraded routing still serves
        handle = door.submit(_requests(1)[0])
        assert handle.result(timeout=120) is not None


def test_foreign_triggers_are_ignored():
    with _door(replicas=2) as door:
        obs_flight.trigger(
            "backend_demoted", trace_id="someone-else/job-1",
            from_backend="jax",
        )
        obs_flight.trigger("pool_exhausted",
                           trace_id="consensus:r0/job-996")
        obs_flight.trigger("backend_demoted", trace_id=None)
        states = [r["state"] for r in door.replica_stats()]
    assert states == [serve_replicas.UP, serve_replicas.UP]


def test_close_detaches_listener():
    door = _door(replicas=2)
    r0_name = door._replicas[0].name
    door.close()
    # triggers after close must not touch the (closed) door's state
    obs_flight.trigger(
        "backend_demoted", trace_id=f"{r0_name}/job-995",
        from_backend="jax",
    )
    assert door._replicas[0].state == serve_replicas.UP


# ------------------------------------------------- flight trigger stream


def test_trigger_listeners_receive_and_survive_errors():
    calls = []

    def listener(reason, trace_id, detail):
        calls.append((reason, trace_id, dict(detail)))

    def broken(reason, trace_id, detail):
        raise RuntimeError("listener bug")

    obs_flight.add_trigger_listener(broken)
    obs_flight.add_trigger_listener(listener)
    obs_flight.add_trigger_listener(listener)  # dedupe by identity
    try:
        obs_flight.trigger("unit_test_reason", trace_id="t/1", k=1)
        # repeated (reason, trace) is deduped by the recorder but the
        # listener stream sees every firing (health must not miss one)
        obs_flight.trigger("unit_test_reason", trace_id="t/1", k=2)
    finally:
        obs_flight.remove_trigger_listener(listener)
        obs_flight.remove_trigger_listener(broken)
    assert calls == [
        ("unit_test_reason", "t/1", {"k": 1}),
        ("unit_test_reason", "t/1", {"k": 2}),
    ]
    obs_flight.trigger("unit_test_reason", trace_id="t/2")
    assert len(calls) == 2  # removed listeners stay silent


# ---------------------------------------------------------- stats payload


def test_front_door_publishes_replica_table(monkeypatch, tmp_path):
    stats_file = tmp_path / "stats.json"
    monkeypatch.setenv("WAFFLE_STATS_FILE", str(stats_file))
    with _door(replicas=2) as door:
        for h in door.submit_all(_requests(2)):
            h.result(timeout=120)
    payload = json.loads(stats_file.read_text())
    assert payload["service"] == "consensus"
    table = payload["replicas"]
    assert [r["replica"] for r in table] == [
        "consensus:r0", "consensus:r1",
    ]
    for rep in table:
        assert rep["state"] == serve_replicas.UP
        assert "outstanding" in rep and "routed" in rep
