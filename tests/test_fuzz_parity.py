"""Randomized differential parity fuzz: the jax-backend engines must
produce byte-identical results (sequences, scores, assignments) to the
Python oracle across randomized workload shapes — error rates, read
counts, haplotype splits, offsets, wildcards, cost models, and
min-counts chosen to exercise the device fast paths (runs, arenas,
forced pushes, fused expansions, on-device discards) against their
per-symbol oracle flow."""

import json

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.config import ConsensusCost
from waffle_con_tpu.utils.example_gen import corrupt, generate_test


def _cfg(backend, rng, **over):
    b = (
        CdwfaConfigBuilder()
        .backend(backend)
        .min_count(over.get("min_count", int(rng.integers(1, 4))))
    )
    if over.get("l2"):
        b = b.consensus_cost(ConsensusCost.L2_DISTANCE)
    if over.get("weighted"):
        b = b.weighted_by_ed(True)
    if over.get("et"):
        b = b.allow_early_termination(True)
    return b.build()


def _assert_parity(tag, want, got):
    """Parity assertion with audit triage: when the decision audit plane
    is on (``WAFFLE_AUDIT=1``) a mismatch first dumps both engines'
    decision logs plus their first-divergence diff as a bundle under
    ``WAFFLE_AUDIT_DIR`` (see ``scripts/waffle_diverge.py diff``), so a
    red fuzz run leaves enough behind to triage without a rerun."""
    if want == got:
        return
    from waffle_con_tpu.obs import audit as obs_audit

    bundle = obs_audit.dump_parity_bundle(tag) if obs_audit.audit_enabled() else None
    assert want == got, f"parity mismatch [{tag}] (audit bundle: {bundle})"


@pytest.mark.parametrize("seed", range(8))
def test_single_engine_fuzz(seed):
    rng = np.random.default_rng(1000 + seed)
    seq_len = int(rng.integers(40, 260))
    n = int(rng.integers(4, 10))
    er = float(rng.choice([0.0, 0.01, 0.03, 0.06]))
    truth, reads = generate_test(4, seq_len, n, er, seed=2000 + seed)
    over = {
        "l2": bool(rng.integers(0, 2)),
        "et": bool(rng.integers(0, 2)),
        "min_count": int(rng.integers(1, max(2, n // 2))),
    }
    engines = []
    for backend in ("python", "jax"):
        e = ConsensusDWFA(_cfg(backend, np.random.default_rng(seed), **over))
        for r in reads:
            e.add_sequence(r)
        engines.append(e)
    want = engines[0].consensus()
    got = engines[1].consensus()
    _assert_parity(
        f"single-fuzz-{seed}",
        [(c.sequence, c.scores) for c in want],
        [(c.sequence, c.scores) for c in got],
    )


@pytest.mark.parametrize("seed", range(8))
def test_dual_engine_fuzz(seed):
    rng = np.random.default_rng(3000 + seed)
    seq_len = int(rng.integers(60, 240))
    half = int(rng.integers(3, 7))
    er = float(rng.choice([0.0, 0.01, 0.04]))
    truth, reads1 = generate_test(4, seq_len, half, er, seed=4000 + seed)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=int(rng.integers(1, 4)), replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    reads = list(reads1) + [
        corrupt(bytes(h2), er, np.random.default_rng(5000 + seed * 16 + i))
        for i in range(half)
    ]
    over = {
        "l2": bool(rng.integers(0, 2)),
        "weighted": bool(rng.integers(0, 2)),
        "min_count": int(rng.integers(1, 4)),
    }
    engines = []
    for backend in ("python", "jax"):
        e = DualConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), **over)
        )
        for r in reads:
            e.add_sequence(r)
        engines.append(e)
    _assert_parity(
        f"dual-fuzz-{seed}", engines[0].consensus(), engines[1].consensus()
    )


def test_parity_bundle_dump(tmp_path, monkeypatch):
    """A parity failure with audit enabled leaves a parseable triage
    bundle (both decision logs + their first-divergence diff) under
    ``WAFFLE_AUDIT_DIR``."""
    monkeypatch.setenv("WAFFLE_AUDIT", "1")
    monkeypatch.setenv("WAFFLE_AUDIT_DIR", str(tmp_path))
    from waffle_con_tpu.obs import audit as obs_audit

    truth, reads = generate_test(4, 60, 5, 0.02, seed=77)
    for backend in ("python", "jax"):
        e = ConsensusDWFA(
            _cfg(backend, np.random.default_rng(7), min_count=2)
        )
        for r in reads:
            e.add_sequence(r)
        e.consensus()
    with pytest.raises(AssertionError, match="audit bundle"):
        _assert_parity("bundle-selftest", ["want"], ["got"])
    bundle = tmp_path / "bundle-bundle-selftest"
    assert bundle.is_dir(), sorted(p.name for p in tmp_path.iterdir())
    logs = sorted(bundle.glob("log-*.jsonl"))
    assert len(logs) == 2
    for log in logs:
        records = obs_audit.load_log(str(log))
        assert records and all("kind" in r for r in records)
    diff = json.loads((bundle / "diff.json").read_text())
    assert diff["tag"] == "bundle-selftest"
    # same workload on both engines: decision maps agree, no divergence
    assert diff["diff"] is None


@pytest.mark.parametrize("seed", range(4))
def test_dual_engine_offset_fuzz(seed):
    """Dual splits WITH late-activating reads: the regression class of
    the arena child-creation `off`-row scatter bug (children created on
    device inherited a stale offset row, visible only one push after
    the arena and only on offset workloads)."""
    rng = np.random.default_rng(8600 + seed)
    seq_len = int(rng.integers(180, 320))
    half = int(rng.integers(4, 6))
    truth, reads1 = generate_test(4, seq_len, half, 0.01, seed=8700 + seed)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=2, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    reads = list(reads1) + [
        corrupt(bytes(h2), 0.01, np.random.default_rng(8800 + seed * 8 + i))
        for i in range(half)
    ]
    offsets = [None] * len(reads)
    for j in range(2):
        off = int(rng.integers(60, seq_len // 2))
        reads.append(
            corrupt(
                reads[j][off:], 0.01, np.random.default_rng(8900 + seed * 8 + j)
            )
        )
        offsets.append(off)
    engines = []
    for backend in ("python", "jax"):
        e = DualConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), min_count=2)
        )
        for r, off in zip(reads, offsets):
            e.add_sequence_offset(r, off)
        engines.append(e)
    assert engines[0].consensus() == engines[1].consensus()


@pytest.mark.parametrize("seed", range(4))
def test_single_engine_offset_fuzz(seed):
    """Late-starting reads: the windowed activation path plus the
    gather-variant (non-uniform-offset) device kernels."""
    rng = np.random.default_rng(6000 + seed)
    seq_len = int(rng.integers(150, 300))
    truth, reads = generate_test(4, seq_len, 4, 0.01, seed=7000 + seed)
    offsets = [int(rng.integers(30, seq_len // 2)) for _ in range(2)]
    engines = []
    for backend in ("python", "jax"):
        e = ConsensusDWFA(_cfg(backend, np.random.default_rng(seed), min_count=2))
        for r in reads:
            e.add_sequence(r)
        for off in offsets:
            e.add_sequence_offset(truth[off:], off)
        engines.append(e)
    want = engines[0].consensus()
    got = engines[1].consensus()
    assert [(c.sequence, c.scores) for c in want] == [
        (c.sequence, c.scores) for c in got
    ]


@pytest.mark.parametrize("seed", range(6))
def test_dual_locked_side_fuzz(seed):
    """Haplotypes of different lengths: the shorter side finishes and
    LOCKS while the longer keeps extending — exercising the
    one-side-locked device run mode against the per-symbol oracle."""
    rng = np.random.default_rng(8000 + seed)
    seq_len = int(rng.integers(80, 200))
    extra = int(rng.integers(20, 60))
    half = int(rng.integers(3, 6))
    er = float(rng.choice([0.0, 0.01, 0.03]))
    truth, reads1 = generate_test(4, seq_len, half, er, seed=9000 + seed)
    tail, _ = generate_test(4, extra, 1, 0.0, seed=9500 + seed)
    h2 = bytearray(truth)
    h2[seq_len // 2] = (h2[seq_len // 2] + 1) % 4
    h2 = bytes(h2) + tail
    reads = list(reads1) + [
        corrupt(h2, er, np.random.default_rng(9800 + seed * 16 + i))
        for i in range(half)
    ]
    engines = []
    for backend in ("python", "jax"):
        e = DualConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), min_count=2)
        )
        for r in reads:
            e.add_sequence(r)
        engines.append(e)
    assert engines[0].consensus() == engines[1].consensus()


@pytest.mark.parametrize("seed", range(4))
def test_single_engine_wildcard_fuzz(seed):
    """Wildcard reads (the '*' base matches anything): exercises the
    kernels' wildcard vote-drop and match paths against the oracle."""
    rng = np.random.default_rng(11000 + seed)
    seq_len = int(rng.integers(50, 160))
    truth, reads = generate_test(4, seq_len, 6, 0.02, seed=12000 + seed)
    star = ord("*")
    wc_reads = []
    for r in reads:
        arr = bytearray(r)
        for pos in rng.choice(
            len(arr), size=max(1, len(arr) // 20), replace=False
        ):
            arr[pos] = star
        wc_reads.append(bytes(arr))
    engines = []
    for backend in ("python", "jax"):
        cfg = (
            CdwfaConfigBuilder()
            .backend(backend)
            .min_count(2)
            .wildcard(star)
            .build()
        )
        e = ConsensusDWFA(cfg)
        for r in wc_reads:
            e.add_sequence(r)
        engines.append(e)
    want = engines[0].consensus()
    got = engines[1].consensus()
    assert [(c.sequence, c.scores) for c in want] == [
        (c.sequence, c.scores) for c in got
    ]


@pytest.mark.parametrize("seed", range(4))
def test_reached_end_absorption_fuzz(seed):
    """Staggered exact-prefix reads reach the end of their baseline at
    different steps mid-run, so the lean device step's fused reached-end
    absorption (folded into the vote count, no materialized occupancy
    tensor) fires repeatedly against live votes from the full reads."""
    rng = np.random.default_rng(17000 + seed)
    seq_len = int(rng.integers(120, 260))
    truth, reads = generate_test(4, seq_len, 5, 0.01, seed=18000 + seed)
    reads = list(reads)
    for frac in (0.3, 0.5, 0.7, 0.9):
        cut = int(seq_len * frac) + int(rng.integers(0, 6))
        reads.append(truth[:cut])
    engines = []
    for backend in ("python", "jax"):
        e = ConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), min_count=3)
        )
        for r in reads:
            e.add_sequence(r)
        engines.append(e)
    want = engines[0].consensus()
    got = engines[1].consensus()
    assert [(c.sequence, c.scores) for c in want] == [
        (c.sequence, c.scores) for c in got
    ]


@pytest.mark.parametrize("seed", range(4))
def test_near_tie_vote_fuzz(seed):
    """Exact 50/50 vote ties sitting at the min_count threshold: the
    same positions are flipped in exactly half of otherwise error-free
    reads, so the fused vote counting must break ties (VOTE_EPS
    ordering) and gate the threshold identically to the oracle."""
    rng = np.random.default_rng(19000 + seed)
    seq_len = int(rng.integers(60, 180))
    n = int(rng.choice([4, 6, 8]))
    truth, reads = generate_test(4, seq_len, n, 0.0, seed=20000 + seed)
    reads = [bytearray(r) for r in reads]
    for pos in rng.choice(seq_len, size=3, replace=False):
        alt = (truth[pos] + 1 + int(rng.integers(3))) % 4
        for i in range(n // 2):
            if pos < len(reads[i]):
                reads[i][pos] = alt
    reads = [bytes(r) for r in reads]
    engines = []
    for backend in ("python", "jax"):
        e = ConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), min_count=n // 2)
        )
        for r in reads:
            e.add_sequence(r)
        engines.append(e)
    want = engines[0].consensus()
    got = engines[1].consensus()
    assert [(c.sequence, c.scores) for c in want] == [
        (c.sequence, c.scores) for c in got
    ]


@pytest.mark.parametrize("seed", range(4))
def test_i16_band_state_fuzz(seed, monkeypatch):
    """Forced int16 band-state narrowing (``WAFFLE_XLA_I16=1``, normally
    TPU-only): the narrowed while-loop kernels must stay bit-identical
    to the oracle on single AND dual workloads wherever the
    ``_xla_i16_ok`` geometry bound admits narrowing."""
    monkeypatch.setenv("WAFFLE_XLA_I16", "1")
    rng = np.random.default_rng(21000 + seed)
    seq_len = int(rng.integers(80, 220))
    n = int(rng.integers(4, 8))
    er = float(rng.choice([0.0, 0.01, 0.04]))
    truth, reads = generate_test(4, seq_len, n, er, seed=22000 + seed)
    engines = []
    for backend in ("python", "jax"):
        e = ConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), min_count=2)
        )
        for r in reads:
            e.add_sequence(r)
        engines.append(e)
    want = engines[0].consensus()
    got = engines[1].consensus()
    assert [(c.sequence, c.scores) for c in want] == [
        (c.sequence, c.scores) for c in got
    ]

    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=2, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    dual_reads = list(reads) + [
        corrupt(bytes(h2), er, np.random.default_rng(23000 + seed * 16 + i))
        for i in range(n)
    ]
    dual_engines = []
    for backend in ("python", "jax"):
        e = DualConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), min_count=2)
        )
        for r in dual_reads:
            e.add_sequence(r)
        dual_engines.append(e)
    assert dual_engines[0].consensus() == dual_engines[1].consensus()


# ---------------------------------------------------------------------------
# Speculative K-column stepping (WAFFLE_RUN_COLS): the device while-loop
# processes K columns per iteration, re-verifying in-kernel and freezing on
# the first stop code — the contract is BYTE-IDENTICAL results to K=1 for
# every K, regardless of where within a K-block the stop lands.
# ---------------------------------------------------------------------------


def _single_result(reads, k, monkeypatch, min_count=2, backend="jax"):
    monkeypatch.setenv("WAFFLE_RUN_COLS", str(k))
    e = ConsensusDWFA(
        _cfg(backend, np.random.default_rng(0), min_count=min_count)
    )
    for r in reads:
        e.add_sequence(r)
    return [(c.sequence, c.scores) for c in e.consensus()]


@pytest.mark.parametrize("offset", range(4))
def test_spec_block_divergence_every_offset(offset, monkeypatch):
    """Force the stop to land at EVERY offset within a K=4 block: the
    stopping step is pinned by the sequence length, so sweeping four
    consecutive lengths walks the stop across all in-block positions.
    The committed prefix must be byte-identical to K=1 and the oracle
    at each offset (rollback-at-offset-0 is the offset=0 case)."""
    seq_len = 96 + offset
    truth, reads = generate_test(4, seq_len, 6, 0.01, seed=26000 + offset)
    want = _single_result(reads, 1, monkeypatch, backend="python")
    base = _single_result(reads, 1, monkeypatch)
    spec = _single_result(reads, 4, monkeypatch)
    assert base == want
    assert spec == base


@pytest.mark.parametrize("seed", range(2))
def test_spec_block_boundary_near_tie(seed, monkeypatch):
    """Near-tie votes pinned AT a K-block boundary: positions K-1, K,
    K+1 of a block edge are flipped in exactly half the reads, so the
    host arbitration stop lands on the boundary and the speculative
    block must roll back without committing a single phantom column."""
    rng = np.random.default_rng(27000 + seed)
    K = 4
    seq_len = 120
    n = 6
    truth, reads = generate_test(4, seq_len, n, 0.0, seed=28000 + seed)
    reads = [bytearray(r) for r in reads]
    edge = K * int(rng.integers(8, 20))
    for pos in (edge - 1, edge, edge + 1):
        alt = (truth[pos] + 1 + int(rng.integers(3))) % 4
        for i in range(n // 2):
            reads[i][pos] = alt
    reads = [bytes(r) for r in reads]
    want = _single_result(
        reads, 1, monkeypatch, min_count=n // 2, backend="python"
    )
    base = _single_result(reads, 1, monkeypatch, min_count=n // 2)
    spec = _single_result(reads, K, monkeypatch, min_count=n // 2)
    assert base == want
    assert spec == base


@pytest.mark.parametrize("seed", range(2))
def test_spec_reached_end_mid_block(seed, monkeypatch):
    """Staggered exact-prefix reads whose baselines end at non-multiples
    of K: the fused reached-end absorption fires MID speculative block,
    and the band can grow in-block on the survivors — both must leave
    the committed prefix byte-identical to K=1."""
    rng = np.random.default_rng(29000 + seed)
    K = 4
    seq_len = 140
    truth, reads = generate_test(4, seq_len, 5, 0.01, seed=30000 + seed)
    reads = list(reads)
    for frac in (0.3, 0.55, 0.8):
        cut = int(seq_len * frac)
        cut += (K - cut % K) % K + 1 + int(rng.integers(0, K - 1))
        reads.append(truth[:cut])  # baseline ends mid-block by design
    want = _single_result(reads, 1, monkeypatch, min_count=3, backend="python")
    base = _single_result(reads, 1, monkeypatch, min_count=3)
    spec = _single_result(reads, K, monkeypatch, min_count=3)
    assert base == want
    assert spec == base


def test_spec_i16_single_and_dual(monkeypatch):
    """Forced int16 band state combined with K>1 speculation (an odd K
    that never divides the stop step evenly), single AND dual: the
    narrowed kernels' freeze masking must stay bit-identical to K=1."""
    monkeypatch.setenv("WAFFLE_XLA_I16", "1")
    rng = np.random.default_rng(31000)
    seq_len = 110
    n = 6
    truth, reads = generate_test(4, seq_len, n, 0.01, seed=32000)
    base = _single_result(reads, 1, monkeypatch)
    spec = _single_result(reads, 5, monkeypatch)
    assert spec == base

    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=2, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    dual_reads = list(reads) + [
        corrupt(bytes(h2), 0.01, np.random.default_rng(33000 + i))
        for i in range(n)
    ]

    def dual_at(k):
        monkeypatch.setenv("WAFFLE_RUN_COLS", str(k))
        e = DualConsensusDWFA(
            _cfg("jax", np.random.default_rng(0), min_count=2)
        )
        for r in dual_reads:
            e.add_sequence(r)
        return e.consensus()

    assert dual_at(5) == dual_at(1)


# ---------------------------------------------------------------------------
# Frontier-parallel speculation (WAFFLE_FRONTIER_M): alongside each engaged
# run the engine gangs the next-best M-1 queued branches through the ragged
# kernel; peers' advances wait as consume-once deposits validated against
# the real pop's arguments — the contract is BYTE-IDENTICAL results to M=1
# for every M, on any workload shape.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(4))
def test_frontier_gang_fuzz(seed, monkeypatch):
    """Random M x workload grid: noisy depths where gangs fire and
    near-tie flips where predictions go stale mid-queue — every draw
    must match the oracle and the M=1 run byte-for-byte."""
    rng = np.random.default_rng(34000 + seed)
    m = int(rng.choice([2, 4, 8]))
    seq_len = int(rng.integers(120, 260))
    n = int(rng.integers(6, 10))
    er = float(rng.choice([0.02, 0.04]))
    truth, reads = generate_test(4, seq_len, n, er, seed=35000 + seed)
    reads = [bytearray(r) for r in reads]
    # sprinkle exact half-ties on top of the noise so some speculated
    # pops lose their predicted ordering (mispredict-discard coverage)
    for pos in rng.choice(seq_len, size=2, replace=False):
        alt = (truth[pos] + 1 + int(rng.integers(3))) % 4
        for i in range(n // 2):
            if pos < len(reads[i]):
                reads[i][pos] = alt
    reads = [bytes(r) for r in reads]
    mc = int(rng.integers(2, max(3, n // 2)))

    def run(backend, width):
        monkeypatch.setenv("WAFFLE_FRONTIER_M", str(width))
        e = ConsensusDWFA(_cfg(backend, np.random.default_rng(seed),
                               min_count=mc))
        for r in reads:
            e.add_sequence(r)
        return [(c.sequence, c.scores) for c in e.consensus()]

    want = run("python", 1)
    base = run("jax", 1)
    spec = run("jax", m)
    assert base == want
    assert spec == base


@pytest.mark.parametrize("seed", range(2))
def test_frontier_gang_dual_fuzz(seed, monkeypatch):
    """Dual-engine draws at random M: only single-side branches gang
    (dual nodes need the paired kernel), and the result must stay
    byte-identical to M=1 and the oracle."""
    rng = np.random.default_rng(36000 + seed)
    m = int(rng.choice([2, 4, 8]))
    seq_len = int(rng.integers(140, 260))
    half = int(rng.integers(3, 6))
    er = float(rng.choice([0.02, 0.04]))
    truth, reads1 = generate_test(4, seq_len, half, er, seed=37000 + seed)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=3, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    reads = list(reads1) + [
        corrupt(bytes(h2), er, np.random.default_rng(38000 + seed * 16 + i))
        for i in range(half)
    ]

    def run(backend, width):
        monkeypatch.setenv("WAFFLE_FRONTIER_M", str(width))
        e = DualConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), min_count=2)
        )
        for r in reads:
            e.add_sequence(r)
        return e.consensus()

    want = run("python", 1)
    base = run("jax", 1)
    spec = run("jax", m)
    assert base == want
    assert spec == base


@pytest.mark.parametrize("seed", range(4))
def test_priority_chain_fuzz(seed):
    """Two-level chains with a level-1 split: the priority engine's
    worklist + shared-scorer views against the oracle."""
    from waffle_con_tpu.models.priority_consensus import (
        PriorityConsensusDWFA,
    )

    rng = np.random.default_rng(13000 + seed)
    n = int(rng.integers(6, 12))
    l0_len = int(rng.integers(40, 120))
    l1_len = int(rng.integers(60, 160))
    er = float(rng.choice([0.0, 0.02]))
    t0, level0 = generate_test(4, l0_len, n, er, seed=14000 + seed)
    t1a, _ = generate_test(4, l1_len, 1, 0.0, seed=15000 + seed)
    t1b = bytearray(t1a)
    t1b[l1_len // 2] = (t1b[l1_len // 2] + 1) % 4
    t1b = bytes(t1b)
    chains = []
    for i in range(n):
        lvl1 = corrupt(
            t1a if i < n // 2 else t1b,
            er,
            np.random.default_rng(16000 + seed * 32 + i),
        )
        chains.append([level0[i], lvl1])
    engines = []
    for backend in ("python", "jax"):
        e = PriorityConsensusDWFA(
            _cfg(backend, np.random.default_rng(seed), min_count=2)
        )
        for c in chains:
            e.add_sequence_chain(c)
        engines.append(e)
    assert engines[0].consensus() == engines[1].consensus()


@pytest.mark.serve
@pytest.mark.parametrize("seed", range(4))
def test_mixed_width_gang_fuzz(seed, monkeypatch):
    """Randomized mixed-width gangs through the stride-masked ragged
    kernel: members with randomized band seeds (distinct pow2 E
    geometries), read counts, and lengths must stay step/code/append/
    stats-identical to the solo ``run_extend`` path every round."""
    from waffle_con_tpu.config import CdwfaConfig
    from waffle_con_tpu.ops import ragged
    from waffle_con_tpu.ops.jax_scorer import JaxScorer

    monkeypatch.setenv("WAFFLE_RAGGED", "1")
    ragged.reset_arena()
    big = 10**9
    rng = np.random.default_rng(17000 + seed)
    n_jobs = int(rng.integers(2, 5))
    jobs, bands = [], []
    for j in range(n_jobs):
        n = int(rng.integers(3, 8))
        length = int(rng.integers(50, 160))
        _, reads = generate_test(
            4, length, n, 0.03, seed=17500 + 32 * seed + j
        )
        jobs.append(list(reads))
        bands.append(int(rng.choice([4, 8, 12, 20, 24])))
    try:
        solos = [
            JaxScorer(r, CdwfaConfig(initial_band=b))
            for r, b in zip(jobs, bands)
        ]
        rags = [
            JaxScorer(r, CdwfaConfig(initial_band=b))
            for r, b in zip(jobs, bands)
        ]
        hs_s = [s.root(np.ones(len(j), bool)) for s, j in zip(solos, jobs)]
        hs_r = [s.root(np.ones(len(j), bool)) for s, j in zip(rags, jobs)]
        cons_s = [b""] * n_jobs
        cons_r = [b""] * n_jobs
        for rnd in range(3):
            ms = int(rng.integers(4, 12))
            solo_out = [
                s.run_extend(h, c, big, big, 0, 2, False, ms,
                             allow_records=False)
                for s, h, c in zip(solos, hs_s, cons_s)
            ]
            args_list = [
                (h, c, big, big, 0, 2, False, ms)
                for h, c in zip(hs_r, cons_r)
            ]
            specs = []
            for s, a in zip(rags, args_list):
                spec = ragged.probe((s.ragged_run_probe, a, {}))
                assert spec is not None
                specs.append(spec)
            ragged.run_group(specs)
            rag_out = [s.run_extend(*a) for s, a in zip(rags, args_list)]
            for g, (so, ro) in enumerate(zip(solo_out, rag_out)):
                ctx = f"seed {seed} round {rnd} job {g}"
                assert so[:3] == ro[:3], ctx
                np.testing.assert_array_equal(so[3].eds, ro[3].eds, ctx)
                np.testing.assert_array_equal(so[3].occ, ro[3].occ, ctx)
                np.testing.assert_array_equal(
                    so[3].split, ro[3].split, ctx
                )
                np.testing.assert_array_equal(
                    so[3].reached, ro[3].reached, ctx
                )
                cons_s[g] += so[2]
                cons_r[g] += ro[2]
    finally:
        ragged.reset_arena()
