"""Ragged cross-job batching over the paged band-state arena.

Core claims under test: (1) mixed-geometry jax jobs served concurrently
gang into shared ragged kernel calls and still return results
byte-identical to serial execution; (2) the page table gives typed
backpressure (:class:`ArenaExhausted`) on exhaustion and the serve path
degrades to bucketed/solo dispatch instead of failing jobs; (3) pages
recycle after release; (4) a supervisor backend demotion releases the
demoted scorer's pages; (5) the ragged kernel itself is step/code/
append/stats-identical to the solo ``run_extend`` path.
"""

import numpy as np
import pytest

from waffle_con_tpu import CdwfaConfigBuilder
from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.ops import ragged
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.runtime import events
from waffle_con_tpu.runtime.supervisor import BackendSupervisor
from waffle_con_tpu.serve import (
    ArenaExhausted,
    ConsensusService,
    JobRequest,
    ServeConfig,
)
from waffle_con_tpu.serve.service import _build_engine
from waffle_con_tpu.utils.example_gen import generate_test

pytestmark = pytest.mark.serve

BIG = 10**9


@pytest.fixture
def arena_env(monkeypatch):
    """Force ragged dispatch on and give the test a fresh arena (the
    singleton re-reads the WAFFLE_RAGGED_* knobs on next use)."""
    monkeypatch.setenv("WAFFLE_RAGGED", "1")
    ragged.reset_arena()
    yield
    ragged.reset_arena()


def _jax_cfg(**kw):
    b = CdwfaConfigBuilder().backend("jax")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _mixed_geometry_requests():
    """Eight jax jobs across distinct (num_reads, length) geometries —
    different shape buckets, so only the ragged path can batch them."""
    shapes = [
        (4, 90), (7, 140), (3, 60), (10, 200),
        (5, 120), (6, 180), (4, 250), (8, 100),
    ]
    requests = []
    for seed, (n, length) in enumerate(shapes):
        _, reads = generate_test(n, length, 6, 0.02, seed=seed)
        cfg = _jax_cfg(min_count=max(2, n // 4))
        requests.append(
            JobRequest(kind="single", reads=tuple(reads), config=cfg)
        )
    return requests


# ----------------------------------------------- serve parity (tentpole)


def test_mixed_geometry_serve_parity_with_gangs(arena_env):
    requests = _mixed_geometry_requests()
    expected = [_build_engine(r).consensus() for r in requests]

    with ConsensusService(
        ServeConfig(workers=8, batch_window_s=0.05, max_batch=8)
    ) as svc:
        handles = svc.submit_all(requests)
        results = [h.result(timeout=300) for h in handles]
        stats = svc.stats()

    for req, got, want in zip(requests, results, expected):
        assert got == want, "ragged-served job diverged from serial"
    assert stats["jobs"]["failed"] == 0

    arena = stats["ragged"]
    # cross-bucket gangs actually formed, and job completion released
    # every page back to the pool
    assert arena["groups"] >= 1
    assert arena["members"] >= 2
    assert arena["admits"] == arena["releases"]
    assert arena["pages_used"] == 0
    assert arena["member_store_failures"] == 0


# ----------------------------------------------- exhaustion backpressure


def test_page_table_exhaustion_is_typed():
    pt = ragged.PageTable(n_pages=2, page_rows=8)
    rows = pt.alloc(1, 8)
    assert rows.tolist() == list(range(8))
    pt.alloc(2, 5)  # rounds up to one page
    assert pt.free_pages == 0
    with pytest.raises(ArenaExhausted):
        pt.alloc(3, 1)
    # release recycles; LIFO hands the freed page straight back
    assert pt.release(2)
    assert pt.free_pages == 1
    assert pt.alloc(3, 3).tolist() == list(range(8, 16))
    assert not pt.release(99)


def test_admit_exhaustion_degrades_and_pages_recycle(arena_env, monkeypatch):
    monkeypatch.setenv("WAFFLE_RAGGED_ROWS", "16")
    monkeypatch.setenv("WAFFLE_RAGGED_PAGE", "8")
    ragged.reset_arena()

    _, reads = generate_test(8, 60, 6, 0.02, seed=11)
    with ragged.serve_scope():
        scorers = [JaxScorer(tuple(reads), CdwfaConfig()) for _ in range(3)]
    arena = ragged.get_arena()
    # two 8-read jobs fill the two pages; the third admit reports
    # exhaustion as a graceful None (probe falls back to solo), with
    # the typed counter bumped
    assert arena.try_admit(scorers[0], job_id=1) is not None
    assert arena.try_admit(scorers[1], job_id=2) is not None
    assert arena.try_admit(scorers[2], job_id=3) is None
    assert arena.stats()["exhausted"] == 1
    # re-admission of a resident scorer is idempotent, not a new alloc
    assert arena.try_admit(scorers[0], job_id=1) is not None
    assert arena.stats()["admits"] == 2

    # release one member: its pages recycle to the waiting third job
    arena.release_scorer(scorers[0])
    rows = arena.try_admit(scorers[2], job_id=3)
    assert rows is not None and len(rows) == 8
    arena.release_job(2)
    arena.release_scorer(scorers[2])
    st = arena.stats()
    assert st["pages_used"] == 0
    assert st["pages_free"] == st["pages_total"]


def test_tiny_pool_serve_still_byte_identical(arena_env, monkeypatch):
    """With a pool too small for most jobs, serving must complete with
    full parity anyway — exhausted probes just run bucketed/solo."""
    monkeypatch.setenv("WAFFLE_RAGGED_ROWS", "8")
    monkeypatch.setenv("WAFFLE_RAGGED_PAGE", "8")
    ragged.reset_arena()
    requests = _mixed_geometry_requests()[:4]
    expected = [_build_engine(r).consensus() for r in requests]
    with ConsensusService(
        ServeConfig(workers=4, batch_window_s=0.02, max_batch=8)
    ) as svc:
        handles = svc.submit_all(requests)
        results = [h.result(timeout=300) for h in handles]
    assert results == expected


# ----------------------------------------------- supervisor demotion


@pytest.mark.faultinject
def test_supervisor_demotion_releases_pages(arena_env, faults):
    cfg = _jax_cfg(
        min_count=1, backend_chain=("python",), dispatch_retries=1,
        breaker_threshold=2, retry_backoff_s=0.0,
    )
    reads = (b"ACGTACGTACGT", b"ACGTACGTACGT", b"ACCTACGTACGT")
    with ragged.serve_scope():
        sup = BackendSupervisor(reads, cfg)
    inner = sup._scorer
    arena = ragged.get_arena()
    assert arena.try_admit(inner, job_id=42) is not None
    assert arena.stats()["pages_used"] > 0

    # every jax dispatch now faults: first root() fails, its retry
    # fails, the breaker trips -> demotion to python mid-residency
    faults.add("timeout", backend="jax", count=None)
    sup.root(np.ones(len(reads), dtype=bool))
    demotions = events.get_events("backend_demoted")
    assert [(d["from_backend"], d["to_backend"]) for d in demotions] == [
        ("jax", "python")
    ]
    st = arena.stats()
    assert st["pages_used"] == 0
    assert st["releases"] == 1


# ----------------------------------------------- direct kernel parity


def _mutated_reads(n, lo, hi, seed):
    r = np.random.default_rng(seed)
    base = r.integers(0, 4, size=int(r.integers(lo, hi))).astype(np.uint8)
    reads = []
    for _ in range(n):
        b = base.copy()
        m = r.random(len(b)) < 0.03
        b[m] = r.integers(0, 4, int(m.sum())).astype(np.uint8)
        reads.append(bytes(b))
    return reads


def test_ragged_kernel_matches_solo_run_extend(arena_env):
    """Mixed-geometry gangs through the ragged kernel step-for-step:
    steps, stop code, appended bytes, and every vote-stats array equal
    the solo ``run_extend`` path across multiple rounds."""
    jobs = [
        _mutated_reads(5, 80, 120, 1),
        _mutated_reads(9, 150, 200, 2),
        _mutated_reads(3, 40, 60, 3),
    ]
    with ragged.serve_scope():
        solos = [JaxScorer(r, CdwfaConfig()) for r in jobs]
        rags = [JaxScorer(r, CdwfaConfig()) for r in jobs]

    hs_s = [s.root(np.ones(len(j), bool)) for s, j in zip(solos, jobs)]
    hs_r = [s.root(np.ones(len(j), bool)) for s, j in zip(rags, jobs)]
    cons_s = [b""] * 3
    cons_r = [b""] * 3
    arena = ragged.get_arena()

    for rnd in range(4):
        solo_out = [
            s.run_extend(h, c, BIG, BIG, 0, 2, False, 8,
                         allow_records=False)
            for s, h, c in zip(solos, hs_s, cons_s)
        ]
        args_list = [
            (h, c, BIG, BIG, 0, 2, False, 8)
            for h, c in zip(hs_r, cons_r)
        ]
        specs = []
        for s, a in zip(rags, args_list):
            spec = ragged.probe((s.ragged_run_probe, a, {}))
            assert spec is not None, "eligible member refused"
            specs.append(spec)
        keys = ragged.run_group(specs)
        assert len(keys) == 3
        rag_out = [s.run_extend(*a) for s, a in zip(rags, args_list)]
        assert all(
            s.counters.get("run_ragged_injected", 0) == rnd + 1
            for s in rags
        )
        for g, (so, ro) in enumerate(zip(solo_out, rag_out)):
            s_steps, s_code, s_app, s_stats, s_rec = so
            r_steps, r_code, r_app, r_stats, r_rec = ro
            ctx = f"round {rnd} job {g}"
            assert (s_steps, s_code, s_app) == (r_steps, r_code, r_app), ctx
            assert s_rec == [] and r_rec == []
            np.testing.assert_array_equal(s_stats.eds, r_stats.eds, ctx)
            np.testing.assert_array_equal(s_stats.occ, r_stats.occ, ctx)
            np.testing.assert_array_equal(s_stats.split, r_stats.split, ctx)
            np.testing.assert_array_equal(
                s_stats.reached, r_stats.reached, ctx
            )
            if s_stats.fin is None:
                assert r_stats.fin is None, ctx
            else:
                np.testing.assert_array_equal(s_stats.fin, r_stats.fin, ctx)
            cons_s[g] += s_app
            cons_r[g] += r_app

    st = arena.stats()
    assert st["groups"] == 4
    assert st["mean_occupancy"] == 3.0
    for s in rags:
        s.ragged_release()
    assert arena.stats()["pages_used"] == 0
