"""Kernel unit tests for the incremental DWFA.

Behavioral parity suite mirroring the reference kernel tests
(``/root/reference/src/dynamic_wfa.rs:267-483``): exact match, single-edit
classes, multi-edit, large indels, finalize semantics, clone equality,
wildcards, early termination, offsets — plus cross-checks against a plain
O(nm) DP edit distance on random pairs.
"""

import numpy as np
import pytest

from waffle_con_tpu.ops.dwfa import DWFAError, DWFALite


def dp_edit_distance(a: bytes, b: bytes, wildcard=None) -> int:
    """Plain dynamic-programming edit distance (baseline-side wildcard)."""
    la, lb = len(a), len(b)
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        curr = [i] + [0] * lb
        for j in range(1, lb + 1):
            match = a[i - 1] == b[j - 1] or a[i - 1] == wildcard
            curr[j] = min(
                prev[j] + 1,
                curr[j - 1] + 1,
                prev[j - 1] + (0 if match else 1),
            )
        prev = curr
    return prev[lb]


def incremental_ed(baseline: bytes, other: bytes, finalize=True) -> int:
    dwfa = DWFALite()
    for i in range(len(other)):
        dwfa.update(baseline, other[: i + 1])
    if finalize:
        dwfa.finalize(baseline, other)
    return dwfa.edit_distance


def test_new():
    dwfa = DWFALite()
    assert dwfa.edit_distance == 0
    assert dwfa.wavefront == [0]


def test_exact_match():
    sequence = b"ACGTACGTACGT"
    dwfa = DWFALite()
    for i in range(len(sequence)):
        assert dwfa.update(sequence, sequence[: i + 1]) == 0


@pytest.mark.parametrize(
    "alt,expected",
    [
        (b"ACGTACCTACGT", 1),  # mismatch
        (b"ACGTACIGTACGT", 1),  # insertion
        (b"ACGTACTACGT", 1),  # deletion
        (b"ACTACGCACGGGT", 4),  # complex
    ],
)
def test_single_and_complex_edits(alt, expected):
    sequence = b"ACGTACGTACGT"
    dwfa = DWFALite()
    for i in range(len(alt)):
        dwfa.update(sequence, alt[: i + 1])
    assert dwfa.edit_distance == expected


def test_one_shot_equals_incremental():
    # 2 deletions, one 2bp insertion, 1 mismatch => 5 edits
    sequence = b"AACGGATCAAGCTTACCAGTATTTACGT"
    alt = b"AACGGACAAAAGCTTACCTGTATTACGT"
    dwfa = DWFALite()
    dwfa.update(sequence, alt)
    assert dwfa.edit_distance == 5
    assert dwfa.edit_distance == incremental_ed(sequence, alt, finalize=False)


def test_big_insertion():
    sequence = b"AACGGATTTTACGT"
    alt = b"AACGGATAAAAGCTTACCTGTTTTACGT"
    assert incremental_ed(sequence, alt, finalize=False) == len(alt) - len(sequence)


def test_big_deletion():
    sequence = b"ATTTTTTTTTTAAAAAAAAAA"
    alt = b"AAAAAAAAAAA"
    assert incremental_ed(sequence, alt, finalize=False) == len(sequence) - len(alt)


def test_required_finalize():
    sequence = b"ATTTTTTTTTTA"
    alt = b"AA"
    dwfa = DWFALite()
    for i in range(len(alt)):
        dwfa.update(sequence, alt[: i + 1])
    # only compared a prefix so far
    assert dwfa.edit_distance == 1
    dwfa.finalize(sequence, alt)
    assert dwfa.edit_distance == len(sequence) - len(alt)


def test_cloning_and_equality():
    sequence = b"AAAAAAA"
    alt = b"AAACAAA"
    dwfa = DWFALite()
    dwfa2 = dwfa.clone()
    for i in range(len(alt)):
        dwfa.update(sequence, sequence[: i + 1])
        dwfa2.update(sequence, alt[: i + 1])
        if sequence[i] == alt[i]:
            assert dwfa == dwfa2
        else:
            assert dwfa != dwfa2
            dwfa2 = dwfa.clone()
    assert dwfa.edit_distance == 0
    assert dwfa2.edit_distance == 0


def test_wildcards_exact():
    consensus = b"AACGGATCAAGCTTACCAGTATTTACGT"
    baseline = b"*ACGGATCAA**TTACCA*TATTTACG*"
    dwfa = DWFALite(wildcard=ord("*"))
    dwfa.update(baseline, consensus)
    assert dwfa.edit_distance == 0


def test_wildcards_with_edits():
    consensus = b"AACGGATCAAGCTTACCAGTATTTACGT"
    baseline = b"*ACGATCAA**TATACCA*TATCTACG*"
    dwfa = DWFALite(wildcard=ord("*"))
    dwfa.update(baseline, consensus)
    assert dwfa.edit_distance == 3


def test_early_termination():
    consensus = b"ACGTACGT"
    baseline = b"ACGT"
    dwfa = DWFALite(allow_early_termination=True)
    dwfa.update(baseline, consensus)
    assert dwfa.edit_distance == 0


def test_big_early_termination():
    # long consensus vs a ~650b prefix read with 2 edits; the early
    # termination must hold the ED at 2 for the whole extension
    rng = np.random.default_rng(1234)
    consensus = bytes(rng.integers(65, 69, size=5000, dtype=np.uint8))
    read = bytearray(consensus[:650])
    read[100] = read[100] ^ 1  # substitution
    del read[400]  # deletion
    read = bytes(read)

    dwfa = DWFALite(allow_early_termination=True)
    for i in range(len(consensus)):
        dwfa.update(read, consensus[: i + 1])
        assert dwfa.edit_distance <= 2
    assert dwfa.edit_distance == 2
    dwfa.finalize(read, consensus)
    assert dwfa.edit_distance == 2


def test_offsets():
    consensus = b"ACGTACGT"
    baseline = b"GTACGT"
    dwfa = DWFALite(allow_early_termination=True)
    dwfa.set_offset(2)
    dwfa.update(baseline, consensus)
    assert dwfa.edit_distance == 0


def test_extension_candidates_votes():
    dwfa = DWFALite()
    baseline = b"ACGT"
    # empty consensus: the root votes for the first baseline char
    assert dwfa.get_extension_candidates(baseline, b"") == {ord("A"): 1}
    dwfa.update(baseline, b"A")
    assert dwfa.get_extension_candidates(baseline, b"A") == {ord("C"): 1}


def spec_final_ed(a: bytes, b: bytes, wildcard=None) -> int:
    """Independent spec for the finalized DWFA edit distance: the smallest
    level ``e`` whose canonical furthest-reaching wavefront consumes all of
    ``b`` (on some diagonal) *and* touches the end of ``a`` (on some
    diagonal).  Note this can undershoot the true end-to-end edit distance
    on adversarial pairs — that is the documented reference semantics
    (``/root/reference/src/dynamic_wfa.rs:201-210``), acceptable for the
    consensus-vs-read domain where sequences are similar."""
    la, lb = len(a), len(b)

    def extend(wf, e):
        for i in range(len(wf)):
            d = wf[i]
            k = i - e
            while d - k < la and d < lb and (
                a[d - k] == b[d] or a[d - k] == wildcard
            ):
                d += 1
            wf[i] = d
        return wf

    def escalate(wf, e):
        new = [0] * (len(wf) + 2)
        for i, d in enumerate(wf):
            new[i] = max(new[i], d)
            new[i + 1] = max(new[i + 1], d + 1)
            new[i + 2] = max(new[i + 2], d + 1)
        return extend(new, e + 1)

    e = 0
    wf = extend([0], 0)
    # phase 1 (update): escalate until all of b is consumed
    while max(wf) < lb:
        wf = escalate(wf, e)
        e += 1
    # phase 2 (finalize): escalate until the end of a is touched
    while max(d - (i - e) for i, d in enumerate(wf)) < la:
        wf = escalate(wf, e)
        e += 1
    return e


def test_random_parity_with_spec():
    rng = np.random.default_rng(7)
    for _ in range(40):
        n = int(rng.integers(1, 60))
        m = int(rng.integers(1, 60))
        a = bytes(rng.integers(0, 4, size=n, dtype=np.uint8))
        b = bytes(rng.integers(0, 4, size=m, dtype=np.uint8))
        got = incremental_ed(a, b)
        assert got == spec_final_ed(a, b)
        # the incremental form never overshoots the true edit distance
        assert got <= dp_edit_distance(a, b)


def test_random_parity_low_edit_pairs():
    # in the intended domain (consensus vs low-error read) the finalized
    # DWFA distance equals the true edit distance
    rng = np.random.default_rng(9)
    for _ in range(30):
        n = int(rng.integers(20, 80))
        a = bytes(rng.integers(0, 4, size=n, dtype=np.uint8))
        b = bytearray(a)
        for _e in range(int(rng.integers(0, 4))):
            pos = int(rng.integers(0, len(b)))
            kind = int(rng.integers(0, 3))
            if kind == 0:
                b[pos] = (b[pos] + 1 + int(rng.integers(0, 3))) % 4
            elif kind == 1 and len(b) > 1:
                del b[pos]
            else:
                b.insert(pos, int(rng.integers(0, 4)))
        b = bytes(b)
        assert incremental_ed(a, b) == spec_final_ed(a, b)
        assert incremental_ed(a, b) <= dp_edit_distance(a, b)


def test_random_parity_with_spec_wildcard():
    rng = np.random.default_rng(8)
    wc = 9
    for _ in range(25):
        n = int(rng.integers(1, 40))
        m = int(rng.integers(1, 40))
        a = bytearray(rng.integers(0, 4, size=n, dtype=np.uint8))
        for i in range(n):
            if rng.random() < 0.15:
                a[i] = wc
        b = bytes(rng.integers(0, 4, size=m, dtype=np.uint8))
        dwfa = DWFALite(wildcard=wc)
        for i in range(m):
            dwfa.update(bytes(a), b[: i + 1])
        dwfa.finalize(bytes(a), b)
        assert dwfa.edit_distance == spec_final_ed(bytes(a), b, wildcard=wc)
