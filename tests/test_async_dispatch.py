"""The async dispatch seam (``DeferredStats``): deferred bulk-stats
fetches must stay invisible to every consumer.

Covers the three composition boundaries the seam documents:

* plain engine flow — ``run_extend`` returns a lazily-fetched
  ``BranchStats`` whose arrays match the eager path bit-for-bit, and
  the overlap accounting records the deferral window;
* the supervisor — validation touches ``.eds``/``.split`` INSIDE the
  retry/demote policy boundary, so an injected garbage-stats fault on a
  deferred result is attributed to the right dispatch and replays
  cleanly (byte-identical consensus);
* the serve/coalescing path — a result crossing the dispatcher thread
  boundary is materialized before delivery (deferral is only safe
  while the consumer is the dispatching thread).
"""

import threading

import numpy as np
import pytest

from waffle_con_tpu import CdwfaConfigBuilder, ConsensusDWFA
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.ops.scorer import (
    BranchStats,
    DeferredStats,
    deferred_sync_enabled,
    host_overlap_total,
    resolve_stats,
)
from waffle_con_tpu.runtime import events
from waffle_con_tpu.utils.example_gen import generate_test

BUDGET = 2**31 - 1


def _scorer(seed=0, n=6, seq_len=80):
    truth, reads = generate_test(4, seq_len, n, 0.01, seed=seed)
    cfg = (
        CdwfaConfigBuilder().min_count(2).backend("jax").build()
    )
    return JaxScorer(reads, cfg), truth


def _run(scorer, max_steps=64):
    h = scorer.root(np.ones(len(scorer.reads), dtype=bool))
    steps, code, appended, stats, _recs = scorer.run_extend(
        h, b"", BUDGET, BUDGET, 0, 2, False, max_steps
    )
    return h, steps, code, appended, stats


# ------------------------------------------------------------- the seam


def test_env_knob_default_and_off(monkeypatch):
    monkeypatch.delenv("WAFFLE_ASYNC_SYNC", raising=False)
    assert deferred_sync_enabled()
    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "0")
    assert not deferred_sync_enabled()


def test_run_extend_defers_and_matches_eager(monkeypatch):
    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "1")
    scorer, _ = _scorer()
    h, steps, code, appended, stats = _run(scorer)
    assert isinstance(stats, DeferredStats)
    assert isinstance(stats, BranchStats)  # duck-types everywhere
    scorer.free(h)

    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "0")
    scorer2, _ = _scorer()
    h2, steps2, code2, appended2, eager = _run(scorer2)
    assert not isinstance(eager, DeferredStats)
    assert (steps, code, appended) == (steps2, code2, appended2)
    np.testing.assert_array_equal(stats.eds, eager.eds)
    np.testing.assert_array_equal(stats.occ, eager.occ)
    np.testing.assert_array_equal(stats.split, eager.split)
    np.testing.assert_array_equal(stats.reached, eager.reached)
    np.testing.assert_array_equal(stats.fin, eager.fin)


def test_overlap_accounting_and_single_fetch(monkeypatch):
    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "1")
    scorer, _ = _scorer(seed=1)
    before = host_overlap_total()
    h, *_rest, stats = _run(scorer)
    assert stats._value is None  # nothing fetched yet
    stats.eds  # first touch resolves...
    mid = host_overlap_total()
    assert mid > before  # ...and books the deferral window
    stats.occ  # second touch reuses the materialized value
    assert host_overlap_total() == mid
    scorer.free(h)


def test_deferred_setter_writes_through(monkeypatch):
    """``faults.mangle_stats`` SETS ``.eds``/``.split`` on dispatch
    results — the deferred proxy must resolve then write through."""
    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "1")
    scorer, _ = _scorer(seed=2)
    h, *_rest, stats = _run(scorer)
    poison = np.full_like(stats.eds, 7)
    stats.eds = poison
    np.testing.assert_array_equal(stats.eds, poison)
    np.testing.assert_array_equal(stats.resolve().eds, poison)
    scorer.free(h)


def test_resolve_stats_walks_containers(monkeypatch):
    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "1")
    scorer, _ = _scorer(seed=3)
    h, *_rest, stats = _run(scorer)
    out = resolve_stats((1, "x", [stats], None))
    assert stats._value is not None  # forced through the nesting
    assert out[2][0] is stats  # structure unchanged
    scorer.free(h)


# ----------------------------------------------------------- supervisor


def _consensus(reads, **kw):
    b = CdwfaConfigBuilder().min_count(1).backend("jax")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    e = ConsensusDWFA(b.build())
    for r in reads:
        e.add_sequence(r)
    return [(c.sequence, c.scores) for c in e.consensus()]


def test_supervisor_validates_deferred_stats_in_boundary(
    faults, monkeypatch
):
    """An injected garbage-stats fault lands on a DEFERRED result: the
    supervisor's validation must force the fetch inside its policy
    boundary, attribute the failure to that dispatch, and replay it —
    final consensus byte-identical to an unfaulted run."""
    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "1")
    reads = (b"ACGTACGTACGT", b"ACGTACGTACGT", b"ACCTACGTACGT")
    expected = _consensus(reads)
    events.clear_events()
    faults.add("garbage", backend="jax", op="stats", count=1)
    got = _consensus(
        reads,
        backend_chain=("python",),
        dispatch_retries=1,
        breaker_threshold=2,
        retry_backoff_s=0.0,
    )
    failed = events.get_events("dispatch_failed")
    assert failed and "GarbageStats" in failed[0]["error"]
    # the retry absorbed the fault: no demotion, byte-identical output
    assert events.get_events("backend_demoted") == []
    assert got == expected


def test_supervisor_demotes_right_handle_with_deferral(faults, monkeypatch):
    """Unlimited garbage faults exhaust retries and demote jax ->
    python with live handles migrated — the deferred seam must not
    smear the fault onto a later dispatch (wrong-handle demotion)."""
    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "1")
    reads = (b"ACGTACGTACGT", b"ACGTACGTACGT", b"ACCTACGTACGT")
    expected = _consensus(reads)
    events.clear_events()
    faults.add("garbage", backend="jax", op="stats", count=None)
    got = _consensus(
        reads,
        backend_chain=("python",),
        dispatch_retries=1,
        breaker_threshold=2,
        retry_backoff_s=0.0,
    )
    demotions = events.get_events("backend_demoted")
    assert [(d["from_backend"], d["to_backend"]) for d in demotions] == [
        ("jax", "python")
    ]
    assert got == expected


# ------------------------------------------------------ serve coalescing


def test_coalesced_dispatch_materializes_deferred_stats(monkeypatch):
    """A deferred result delivered through the batching dispatcher's
    worker handoff must be materialized ON the dispatcher thread — the
    receiving worker never sees an unresolved fetch (fall-through)."""
    from waffle_con_tpu.serve.dispatcher import BatchingDispatcher

    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "1")
    scorer, _ = _scorer(seed=4)
    disp = BatchingDispatcher(window_s=0.05, max_batch=4)
    disp.start()
    disp.job_started()
    disp.job_started()  # >= 2 active jobs so dispatches coalesce
    results = {}
    try:
        def worker(name):
            def fn():
                h, *_rest, stats = _run(scorer)
                scorer.free(h)
                return stats
            results[name] = disp.dispatch(None, ("b",), "run", fn)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 2
        for stats in results.values():
            if isinstance(stats, DeferredStats):
                assert stats._value is not None  # resolved pre-handoff
        assert disp._stats["routed_requests"] >= 1
    finally:
        disp.job_finished()
        disp.job_finished()
        disp.close()


def test_direct_dispatch_keeps_deferral(monkeypatch):
    """A solo job falls through to direct same-thread dispatch — there
    the deferral survives (the consumer IS the dispatching thread)."""
    from waffle_con_tpu.serve.dispatcher import BatchingDispatcher

    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "1")
    scorer, _ = _scorer(seed=5)
    disp = BatchingDispatcher(window_s=0.05, max_batch=4)
    disp.start()
    disp.job_started()  # alone: direct path
    try:
        def fn():
            h, *_rest, stats = _run(scorer)
            scorer.free(h)
            return stats
        stats = disp.dispatch(None, ("b",), "run", fn)
        assert isinstance(stats, DeferredStats)
        assert stats._value is None  # still lazy on the direct path
        stats.eds  # and still resolvable
    finally:
        disp.job_finished()
        disp.close()
