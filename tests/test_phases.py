"""Phase-attributed dispatch profiling (``obs.phases``) and the
search-frontier sampler (``obs.instrument.FrontierSampler``).

Core claims under test:

* conservation — for an eagerly-synced dispatch
  (``WAFFLE_ASYNC_SYNC=0``) the four phases (host_prep /
  device_compute / transfer / host_post) sum to the dispatch wall time
  within 5%, for the solo, dual, AND ragged kernel families;
* zero overhead when disabled — ``begin`` returns ``None``, the scopes
  are the shared no-op singleton, and nothing aggregates;
* the outermost dispatch wins when proxy layers stack;
* a ``DeferredStats`` resolve landing after its dispatch closed is
  flagged ``late`` and still folded into the aggregate as transfer;
* the engines publish per-search phase deltas
  (``report.extra["phases"]``) and decimated frontier samples into the
  flight ring.
"""

import numpy as np
import pytest

from waffle_con_tpu import CdwfaConfigBuilder, ConsensusDWFA
from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import phases
from waffle_con_tpu.obs.instrument import (
    FrontierSampler,
    maybe_instrument,
)
from waffle_con_tpu.ops import ragged
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.utils.example_gen import generate_test

BUDGET = 2**31 - 1


@pytest.fixture
def profiling(monkeypatch):
    """Profiling on, eager stats sync (conservation is exact there),
    clean slate before and after."""
    monkeypatch.setenv("WAFFLE_ASYNC_SYNC", "0")
    phases.enable_profiling(True)
    phases.reset()
    yield
    phases.reset()
    phases.reset_profiling_enabled()


def _timed_scorer(reads):
    cfg = CdwfaConfigBuilder().min_count(2).backend("jax").build()
    return maybe_instrument(JaxScorer(reads, cfg), "jax")


def _assert_conserved(rec):
    ph = rec.phases()
    total = sum(ph.values())
    assert rec.wall_s > 0.0
    assert abs(total - rec.wall_s) <= 0.05 * rec.wall_s + 1e-6, (
        rec.op, rec.wall_s, ph,
    )


# -------------------------------------------------------- conservation


def test_solo_dispatch_phases_conserve(profiling):
    _, reads = generate_test(4, 200, 6, 0.01, seed=0)
    sc = _timed_scorer(reads)
    h = sc.root(np.ones(len(reads), dtype=bool))
    steps, code, app, _stats, _recs = sc.run_extend(
        h, b"", BUDGET, BUDGET, 0, 2, False, 64
    )
    assert steps > 0
    runs = [r for r in phases.recent_records() if r.op == "run"]
    assert runs, [r.op for r in phases.recent_records()]
    rec = runs[-1]
    assert rec.kernel in ("solo", "pallas")
    assert rec.geom.startswith("B")
    assert rec.device_s > 0.0  # the fence attributed kernel time
    _assert_conserved(rec)


def test_dual_dispatch_phases_conserve(profiling):
    _, reads1 = generate_test(4, 150, 6, 0.01, seed=1)
    _, reads2 = generate_test(4, 150, 6, 0.01, seed=2)
    sc = _timed_scorer(list(reads1) + list(reads2))
    n = len(reads1) + len(reads2)
    ha = sc.root(np.ones(n, dtype=bool))
    hb = sc.root(np.ones(n, dtype=bool))
    out = sc.run_extend_dual(
        ha, hb, b"", b"",
        me_budget=BUDGET, other_cost=BUDGET, other_len=0,
        min_count=2, ed_delta=2, imb_min=4, l2=False,
        weighted=False, max_steps=32,
    )
    assert out[0] > 0  # steps
    duals = [r for r in phases.recent_records() if r.op == "run_dual"]
    assert duals, [r.op for r in phases.recent_records()]
    rec = duals[-1]
    assert rec.kernel in ("dual", "pallas")
    assert rec.device_s > 0.0
    _assert_conserved(rec)


@pytest.mark.serve
def test_ragged_group_phases_conserve(profiling, monkeypatch):
    monkeypatch.setenv("WAFFLE_RAGGED", "1")
    ragged.reset_arena()
    try:
        jobs = [
            generate_test(4, 100, 5, 0.02, seed=s)[1] for s in (1, 2)
        ]
        with ragged.serve_scope():
            scorers = [JaxScorer(r, CdwfaConfig()) for r in jobs]
        handles = [
            s.root(np.ones(len(j), bool)) for s, j in zip(scorers, jobs)
        ]
        args_list = [
            (h, b"", BUDGET, BUDGET, 0, 2, False, 8) for h in handles
        ]
        specs = []
        for s, a in zip(scorers, args_list):
            spec = ragged.probe((s.ragged_run_probe, a, {}))
            assert spec is not None
            specs.append(spec)
        keys = ragged.run_group(specs)
        assert len(keys) == len(specs)
        groups = [
            r for r in phases.recent_records() if r.op == "ragged_group"
        ]
        assert groups, [r.op for r in phases.recent_records()]
        rec = groups[-1]
        assert rec.kernel == "ragged"
        assert rec.geom.startswith("P")
        assert rec.device_s > 0.0
        _assert_conserved(rec)
    finally:
        ragged.reset_arena()


# --------------------------------------------- enable/disable contract


def test_disabled_begin_returns_none_and_nothing_aggregates():
    phases.reset_profiling_enabled()
    phases.reset()
    assert not phases.profiling_enabled()
    assert phases.begin("run", "jax") is None
    assert phases.device_scope(None) is phases.NULL_SCOPE
    assert phases.transfer_scope(None) is phases.NULL_SCOPE
    with phases.device_scope(None):
        pass
    assert phases.totals() == {p: 0.0 for p in phases.PHASES}
    assert phases.snapshot() == {}


def test_disabled_timed_scorer_is_unwrapped(monkeypatch):
    monkeypatch.delenv("WAFFLE_PROFILE", raising=False)
    monkeypatch.delenv("WAFFLE_METRICS", raising=False)
    phases.reset_profiling_enabled()
    _, reads = generate_test(4, 60, 4, 0.0, seed=0)
    cfg = CdwfaConfigBuilder().min_count(2).backend("jax").build()
    sc = maybe_instrument(JaxScorer(reads, cfg), "jax")
    assert isinstance(sc, JaxScorer)  # no proxy when everything is off


def test_profiling_enables_timed_scorer(profiling):
    _, reads = generate_test(4, 60, 4, 0.0, seed=0)
    cfg = CdwfaConfigBuilder().min_count(2).backend("jax").build()
    sc = maybe_instrument(JaxScorer(reads, cfg), "jax")
    assert not isinstance(sc, JaxScorer)


def test_outermost_dispatch_wins(profiling):
    outer = phases.begin("run", "jax")
    assert outer is not None
    assert phases.current() is outer
    assert phases.begin("stats", "jax") is None  # nested: suppressed
    phases.end(outer)
    assert phases.current() is None
    snap = phases.snapshot()
    assert list(snap) == ["other/run/k1"]
    assert snap["other/run/k1"]["count"] == 1


def test_late_transfer_is_flagged_and_aggregated(profiling):
    rec = phases.begin("run", "jax")
    rec.annotate(kernel="solo", k=2, geom="B4R8W16")
    with phases.device_scope(rec):
        pass
    phases.end(rec)
    before = phases.totals()["transfer"]
    rec.add_transfer(0.25, 0.0)  # DeferredStats resolving after close
    assert rec.late is True
    assert phases.totals()["transfer"] - before == pytest.approx(0.25)


def test_snapshot_labels_and_mean(profiling):
    rec = phases.begin("run", "jax")
    rec.annotate(kernel="arena", k=4, geom="B8R32W64")
    phases.end(rec)
    snap = phases.snapshot()
    assert "arena/run/k4/B8R32W64" in snap
    row = snap["arena/run/k4/B8R32W64"]
    assert row["count"] == 1
    assert row["mean_ms"] == pytest.approx(row["wall_s"] * 1e3, rel=1e-3)


# ---------------------------------------------------- frontier sampler


def test_frontier_sampler_interval_and_record(monkeypatch):
    monkeypatch.setenv("WAFFLE_FRONTIER_SAMPLE", "8")
    obs_flight.reset()
    sampler = FrontierSampler("single")
    assert sampler.interval == 8
    assert not sampler.due(7)
    assert sampler.due(8) and sampler.due(16)
    sampler.sample(
        8, queue_depth=12, live_branches=3, top_cost=5, next_cost=9,
        top_len=40, farthest=41,
        counters={"run_steps": 90, "run_spec_cols": 100,
                  "run_ragged_injected": 2},
    )
    assert sampler.samples_taken == 1
    recs = [
        r for r in obs_flight.get_recorder().records()
        if r["kind"] == "frontier"
    ]
    assert len(recs) == 1
    r = recs[0]
    assert r["engine"] == "single"
    assert r["pops"] == 8 and r["queue"] == 12 and r["live"] == 3
    assert r["gap"] == 4  # next_cost - top_cost
    assert r["spec_commit_rate"] == pytest.approx(0.9)
    assert r["ragged_injected"] == 2
    obs_flight.reset()


def test_frontier_sampler_disabled_by_zero(monkeypatch):
    monkeypatch.setenv("WAFFLE_FRONTIER_SAMPLE", "0")
    sampler = FrontierSampler("dual")
    assert not any(sampler.due(p) for p in range(1, 200))


# ------------------------------------------------- engine integration


def test_engine_search_publishes_phases_and_frontier(profiling,
                                                     monkeypatch):
    monkeypatch.setenv("WAFFLE_FRONTIER_SAMPLE", "1")
    obs_flight.reset()
    _, reads = generate_test(4, 120, 6, 0.01, seed=5)
    cfg = CdwfaConfigBuilder().min_count(2).backend("jax").build()
    engine = ConsensusDWFA(cfg)
    for r in reads:
        engine.add_sequence(r)
    results = engine.consensus()
    assert results
    report = engine.last_search_report
    ph = report.extra.get("phases")
    assert ph, report.extra
    assert set(ph) == set(phases.PHASES)
    assert sum(ph.values()) > 0.0
    frontier = [
        r for r in obs_flight.get_recorder().records()
        if r["kind"] == "frontier"
    ]
    assert frontier
    assert all(r["engine"] == "single" for r in frontier)
    assert frontier[-1]["pops"] >= frontier[0]["pops"]
    obs_flight.reset()
