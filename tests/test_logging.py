"""Engines end-to-end at DEBUG logging.

The reference CI runs ``RUST_LOG=trace cargo test``
(``/root/reference/.github/workflows/test-ci.yml:13-14``) precisely
because log-formatting code is executable surface — a real v0.4.3 panic
lived inside a ``trace!`` call (``/root/reference/CHANGELOG.md:5-7``).
These tests run every engine with the ``waffle_con_tpu`` logger at
DEBUG and force-format every emitted record.
"""

import logging

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
    PriorityConsensusDWFA,
)


def _formatted_messages(caplog):
    """Force %-formatting of every captured record (the panic-shaped
    path): a bad format string or arg mismatch raises here."""
    return [rec.getMessage() for rec in caplog.records]


def _cfg(**kw):
    b = CdwfaConfigBuilder().min_count(1).backend("jax")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def test_single_engine_debug_logging(caplog):
    with caplog.at_level(logging.DEBUG, logger="waffle_con_tpu"):
        engine = ConsensusDWFA(_cfg())
        for seq in (b"ACGTACGT", b"ACGTACGT", b"ACCTACGT"):
            engine.add_sequence(seq)
        results = engine.consensus()
    assert results[0].sequence == b"ACGTACGT"
    msgs = _formatted_messages(caplog)
    assert any(m.startswith("Offsets:") for m in msgs)
    assert any(m.startswith("search summary:") for m in msgs)


def test_search_summary_info_flag(caplog):
    # log_search_summary promotes the one-line summary to INFO
    with caplog.at_level(logging.INFO, logger="waffle_con_tpu"):
        engine = ConsensusDWFA(_cfg(log_search_summary=True))
        for seq in (b"ACGTACGT", b"ACGTACGT", b"ACCTACGT"):
            engine.add_sequence(seq)
        engine.consensus()
    summaries = [
        rec
        for rec in caplog.records
        if rec.getMessage().startswith("search summary:")
    ]
    assert summaries and summaries[0].levelno == logging.INFO


def test_search_summary_debug_by_default(caplog):
    # without the flag the summary must NOT appear at INFO
    with caplog.at_level(logging.INFO, logger="waffle_con_tpu"):
        engine = ConsensusDWFA(_cfg())
        for seq in (b"ACGTACGT", b"ACGTACGT", b"ACCTACGT"):
            engine.add_sequence(seq)
        engine.consensus()
    msgs = _formatted_messages(caplog)
    assert not any(m.startswith("search summary:") for m in msgs)


def test_single_engine_offset_shift_debug_logging(caplog):
    # all-offset inputs exercise the auto-shift debug line
    with caplog.at_level(logging.DEBUG, logger="waffle_con_tpu"):
        engine = ConsensusDWFA(_cfg(offset_compare_length=4))
        engine.add_sequence_offset(b"ACGTACGTAA", 2)
        engine.add_sequence_offset(b"ACGTACGTAA", 2)
        engine.consensus()
    msgs = _formatted_messages(caplog)
    assert any("shifting all offsets" in m for m in msgs)


def test_dual_engine_debug_logging(caplog):
    with caplog.at_level(logging.DEBUG, logger="waffle_con_tpu"):
        engine = DualConsensusDWFA(_cfg())
        for seq in (b"ACGTACGT", b"ACGTACGT", b"ACTTACGT", b"ACTTACGT"):
            engine.add_sequence(seq)
        results = engine.consensus()
    assert results and results[0].is_dual()
    msgs = _formatted_messages(caplog)
    assert any(m.startswith("Offsets:") for m in msgs)
    assert any(m.startswith("search summary:") for m in msgs)


def test_dual_engine_empty_fallback_warning_logging(caplog):
    # a zero per-length capacity discards every pop, draining the queue
    # with no surviving candidate -> the engine's lone warn path
    # (reference dual_consensus.rs:772-779) must format cleanly too
    with caplog.at_level(logging.DEBUG, logger="waffle_con_tpu"):
        engine = DualConsensusDWFA(_cfg(max_capacity_per_size=0))
        engine.add_sequence(b"ACGT")
        engine.add_sequence(b"ACGT")
        results = engine.consensus()
    assert results[0].consensus1.sequence == b""
    msgs = _formatted_messages(caplog)
    assert any("No consensus found" in m for m in msgs)


def test_single_engine_progress_trace(caplog, monkeypatch):
    # the interval is a module global referenced at pop time, so a tiny
    # value makes every pop emit the heartbeat line
    import waffle_con_tpu.models.consensus as mod

    monkeypatch.setattr(mod, "PROGRESS_LOG_INTERVAL", 1)
    with caplog.at_level(logging.DEBUG, logger="waffle_con_tpu"):
        engine = ConsensusDWFA(_cfg())
        for seq in (b"ACGTACGT", b"ACGTACGT", b"ACCTACGT"):
            engine.add_sequence(seq)
        engine.consensus()
    msgs = _formatted_messages(caplog)
    assert any(m.startswith("search progress:") and "pops" in m for m in msgs)


def test_dual_engine_progress_trace(caplog, monkeypatch):
    import waffle_con_tpu.models.dual_consensus as mod

    monkeypatch.setattr(mod, "PROGRESS_LOG_INTERVAL", 1)
    with caplog.at_level(logging.DEBUG, logger="waffle_con_tpu"):
        engine = DualConsensusDWFA(_cfg())
        for seq in (b"ACGTACGT", b"ACGTACGT", b"ACTTACGT", b"ACTTACGT"):
            engine.add_sequence(seq)
        engine.consensus()
    msgs = _formatted_messages(caplog)
    assert any(m.startswith("search progress:") and "pops" in m for m in msgs)


def test_priority_engine_progress_trace(caplog, monkeypatch):
    import waffle_con_tpu.models.priority_consensus as mod

    monkeypatch.setattr(mod, "PROGRESS_LOG_INTERVAL", 1)
    with caplog.at_level(logging.DEBUG, logger="waffle_con_tpu"):
        engine = PriorityConsensusDWFA(_cfg())
        for chain in ([b"ACGT"], [b"ACGT"], [b"ACTT"], [b"ACTT"]):
            engine.add_sequence_chain(chain)
        engine.consensus()
    msgs = _formatted_messages(caplog)
    assert any(
        m.startswith("search progress:") and "groups solved" in m for m in msgs
    )


def test_priority_engine_debug_logging(caplog):
    with caplog.at_level(logging.DEBUG, logger="waffle_con_tpu"):
        engine = PriorityConsensusDWFA(_cfg())
        for chain in (
            [b"ACGT", b"ACGTACGT"],
            [b"ACGT", b"ACGTACGT"],
            [b"ACTT", b"ACTTACTT"],
            [b"ACTT", b"ACTTACTT"],
        ):
            engine.add_sequence_chain(chain)
        result = engine.consensus()
    assert len(result.consensuses) == 2
    msgs = _formatted_messages(caplog)
    assert any(m.startswith("Calling Dual at level") for m in msgs)
