"""Exact-parity tests: the batched JAX scorer must agree with the pure
Python oracle on every observable (integer edit distances, tip votes,
reached flags) and, through the engines, produce byte-identical
consensus results."""

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.ops.scorer import PythonScorer
from waffle_con_tpu.utils.example_gen import generate_test
from waffle_con_tpu.utils.fixtures import load_dual_fixture


def assert_stats_equal(py, jx, context=""):
    np.testing.assert_array_equal(py.eds, jx.eds, err_msg=f"eds {context}")
    np.testing.assert_array_equal(py.occ, jx.occ, err_msg=f"occ {context}")
    np.testing.assert_array_equal(py.split, jx.split, err_msg=f"split {context}")
    np.testing.assert_array_equal(
        py.reached, jx.reached, err_msg=f"reached {context}"
    )


def mirrored_scorers(reads, **cfg):
    config = CdwfaConfig(**cfg)
    return PythonScorer(reads, config), JaxScorer(reads, config)


def test_push_parity_random_walk():
    rng = np.random.default_rng(3)
    reads = [bytes(rng.integers(0, 4, size=rng.integers(10, 40))) for _ in range(6)]
    py, jx = mirrored_scorers(reads)
    hp = py.root(np.ones(6, dtype=bool))
    hj = jx.root(np.ones(6, dtype=bool))
    assert_stats_equal(py.stats(hp, b""), jx.stats(hj, b""), "root")

    # walk: follow the plurality vote with occasional random symbols, which
    # forces edit-distance escalations
    consensus = b""
    for step in range(18):
        sp = py.stats(hp, consensus)
        if step % 5 == 4:
            sym = int(rng.integers(0, 4))
        else:
            votes = sp.occ.sum(axis=0)
            sym = int(py.symtab[int(np.argmax(votes))])
        consensus += bytes([sym])
        assert_stats_equal(
            py.push(hp, consensus), jx.push(hj, consensus), f"step {step}"
        )

    np.testing.assert_array_equal(
        py.finalized_eds(hp, consensus), jx.finalized_eds(hj, consensus)
    )


def test_clone_and_deactivate_parity():
    rng = np.random.default_rng(4)
    reads = [bytes(rng.integers(0, 4, size=20)) for _ in range(4)]
    py, jx = mirrored_scorers(reads)
    hp = py.root(np.ones(4, dtype=bool))
    hj = jx.root(np.ones(4, dtype=bool))
    consensus = reads[0][:5]
    for i in range(1, len(consensus) + 1):
        py.push(hp, consensus[:i])
        jx.push(hj, consensus[:i])
    hp2 = py.clone(hp)
    hj2 = jx.clone(hj)
    py.deactivate(hp2, 1)
    jx.deactivate(hj2, 1)
    ext = consensus + bytes([reads[0][5]])
    assert_stats_equal(py.push(hp2, ext), jx.push(hj2, ext), "clone+deact")
    # the original branch is untouched by the clone's evolution
    assert_stats_equal(py.stats(hp, consensus), jx.stats(hj, consensus), "orig")
    py.free(hp2)
    jx.free(hj2)
    assert_stats_equal(py.stats(hp, consensus), jx.stats(hj, consensus), "freed")


def test_activation_parity():
    rng = np.random.default_rng(5)
    base = bytes(rng.integers(0, 4, size=24))
    reads = [base, base, base[12:]]
    py, jx = mirrored_scorers(reads, offset_window=5, offset_compare_length=8)
    active = np.array([True, True, False])
    hp = py.root(active)
    hj = jx.root(active)
    consensus = b""
    for i in range(18):
        consensus += bytes([base[i]])
        sp = py.push(hp, consensus)
        sj = jx.push(hj, consensus)
        assert_stats_equal(sp, sj, f"pre-activate {i}")
    py.activate(hp, 2, 12, consensus)
    jx.activate(hj, 2, 12, consensus)
    assert_stats_equal(
        py.stats(hp, consensus), jx.stats(hj, consensus), "post-activate"
    )
    for i in range(18, 24):
        consensus += bytes([base[i]])
        assert_stats_equal(
            py.push(hp, consensus), jx.push(hj, consensus), f"post-activate {i}"
        )
    np.testing.assert_array_equal(
        py.finalized_eds(hp, consensus), jx.finalized_eds(hj, consensus)
    )


def test_wavefront_rebucketing():
    # a read wildly different from the consensus forces e far beyond the
    # initial bucket (E=8), exercising overflow + re-bucket + retry
    reads = [b"\x00" * 24, b"\x01" * 24]
    py, jx = mirrored_scorers(reads)
    hp = py.root(np.ones(2, dtype=bool))
    hj = jx.root(np.ones(2, dtype=bool))
    consensus = b""
    for i in range(24):
        consensus += b"\x00"
        assert_stats_equal(
            py.push(hp, consensus), jx.push(hj, consensus), f"step {i}"
        )
    assert jx._E > JaxScorer.INITIAL_E
    np.testing.assert_array_equal(
        py.finalized_eds(hp, consensus), jx.finalized_eds(hj, consensus)
    )


def test_wildcard_parity():
    reads = [b"\x00\x01\x09\x03" * 4, b"\x00\x01\x02\x03" * 4]
    py, jx = mirrored_scorers(reads, wildcard=9)
    hp = py.root(np.ones(2, dtype=bool))
    hj = jx.root(np.ones(2, dtype=bool))
    consensus = b""
    for sym in b"\x00\x01\x02\x03" * 4:
        consensus += bytes([sym])
        assert_stats_equal(py.push(hp, consensus), jx.push(hj, consensus))


def test_single_engine_backend_parity():
    truth, reads = generate_test(4, 40, 6, 0.02, seed=17)
    results = {}
    for backend in ("python", "jax"):
        engine = ConsensusDWFA(
            CdwfaConfigBuilder().backend(backend).build()
        )
        for r in reads:
            engine.add_sequence(r)
        results[backend] = engine.consensus()
    assert results["python"] == results["jax"]
    assert results["jax"][0].sequence == truth


def test_dual_engine_backend_parity_small():
    # small two-haplotype split: exercises dual splitting, pruning, and
    # result swapping through the JAX scorer at test-friendly size
    sequences = [b"ACGTACGT", b"ACGTACGT", b"AGGTACGT", b"AGGTACGT"]
    results = {}
    for backend in ("python", "jax"):
        engine = DualConsensusDWFA(
            CdwfaConfigBuilder().min_count(1).backend(backend).build()
        )
        for s in sequences:
            engine.add_sequence(s)
        results[backend] = engine.consensus()
    assert results["python"] == results["jax"]
    assert results["jax"][0].is_dual()
    for a, b in zip(results["python"], results["jax"]):
        assert a.scores1 == b.scores1
        assert a.scores2 == b.scores2
        assert a.consensus1.scores == b.consensus1.scores


@pytest.mark.parametrize("weighted", [False, True])
def test_dual_engine_run_extend_parity(weighted):
    """Two noisy haplotypes at a size where the dual device run loop
    (``run_extend_dual``) engages for the clean stretches: results,
    scores, and read assignments must be byte-identical to the oracle."""
    truth, reads1 = generate_test(4, 160, 5, 0.02, seed=29)
    rng = np.random.default_rng(290)
    h2 = bytearray(truth)
    for pos in rng.choice(160, size=2, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    h2 = bytes(h2)
    from waffle_con_tpu.utils.example_gen import corrupt

    reads2 = [
        corrupt(h2, 0.02, np.random.default_rng(300 + i)) for i in range(5)
    ]
    reads = list(reads1) + reads2

    results = {}
    import waffle_con_tpu.models.dual_consensus as dc

    captured = {}
    orig = dc.make_scorer

    def spy(seqs, config):
        scorer = orig(seqs, config)
        captured[config.backend] = scorer
        return scorer

    dc.make_scorer = spy
    try:
        for backend in ("python", "jax"):
            engine = DualConsensusDWFA(
                CdwfaConfigBuilder()
                .min_count(2)
                .weighted_by_ed(weighted)
                .backend(backend)
                .build()
            )
            for r in reads:
                engine.add_sequence(r)
            results[backend] = engine.consensus()
    finally:
        dc.make_scorer = orig

    assert results["python"] == results["jax"]
    for a, b in zip(results["python"], results["jax"]):
        assert a.scores1 == b.scores1
        assert a.scores2 == b.scores2
        assert a.is_consensus1 == b.is_consensus1
    # the device fast path must actually have carried part of the search
    counters = captured["jax"].counters
    assert counters["run_steps"] + counters["run_dual_steps"] > 0


def test_dual_engine_backend_parity_fixture():
    from waffle_con_tpu import ConsensusCost

    sequences, expected = load_dual_fixture(
        "dual_001", True, ConsensusCost.L1_DISTANCE
    )
    results = {}
    for backend in ("python", "jax"):
        engine = DualConsensusDWFA(
            CdwfaConfigBuilder().wildcard(ord("*")).backend(backend).build()
        )
        for s in sequences:
            engine.add_sequence(s)
        results[backend] = engine.consensus()
    assert results["python"] == results["jax"]
    assert results["jax"] == [expected]
    # scores are ignored by equality; compare them explicitly
    for a, b in zip(results["python"], results["jax"]):
        assert a.scores1 == b.scores1
        assert a.scores2 == b.scores2
        assert a.consensus1.scores == b.consensus1.scores


def _run_priority_fixture_jax(name):
    from waffle_con_tpu import PriorityConsensusDWFA
    from waffle_con_tpu.utils.fixtures import load_priority_fixture

    config = CdwfaConfigBuilder().wildcard(ord("*")).backend("jax").build()
    chains, expected = load_priority_fixture(name, True, config.consensus_cost)
    engine = PriorityConsensusDWFA(config)
    for chain in chains:
        engine.add_sequence_chain(chain)
    result = engine.consensus()
    assert result.sequence_indices == expected.sequence_indices
    assert len(result.consensuses) == len(expected.consensuses)
    for got_chain, want_chain in zip(result.consensuses, expected.consensuses):
        for got, want in zip(got_chain, want_chain):
            assert got.sequence == want.sequence


def test_priority_engine_jax_backend_fixture():
    """priority_001 through the full priority → dual → jax-scorer stack."""
    _run_priority_fixture_jax("priority_001")


def test_multi_err_recovery_jax_backend():
    """multi_err_001 (consensus must be *recovered*, not present verbatim)
    through the priority engine on the jax backend."""
    from waffle_con_tpu import PriorityConsensusDWFA
    from waffle_con_tpu.utils.fixtures import load_priority_fixture

    config = CdwfaConfigBuilder().wildcard(ord("*")).backend("jax").build()
    chains, expected = load_priority_fixture(
        "multi_err_001", False, config.consensus_cost
    )
    engine = PriorityConsensusDWFA(config)
    for chain in chains:
        engine.add_sequence_chain(chain)
    result = engine.consensus()
    assert result.sequence_indices == expected.sequence_indices
    for got_chain, want_chain in zip(result.consensuses, expected.consensuses):
        for got, want in zip(got_chain, want_chain):
            assert got.sequence == want.sequence


def test_push_many_duplicate_handle_guard():
    """Duplicate handles in one push batch would race in the scatter;
    the scorer must reject them loudly (VERDICT r3 weak #7)."""
    cfg = CdwfaConfigBuilder().backend("jax").build()
    jx = JaxScorer([b"ACGT", b"ACGT"], cfg)
    h = jx.root(np.array([True, True]))
    with pytest.raises(ValueError, match="duplicate branch handles"):
        jx.push_many([(h, b"A"), (h, b"C")])


def test_clone_push_many_matches_clone_then_push():
    """The fused clone+push dispatch must be bit-identical to the
    separate clone_many + push_many sequence, including clone-only and
    in-place entries."""
    rng = np.random.default_rng(11)
    reads = [bytes(rng.integers(0, 4, size=30)) for _ in range(5)]
    config = CdwfaConfig()
    jx = JaxScorer(reads, config)
    base = bytes(reads[0][:6])
    h = jx.root(np.ones(5, dtype=bool))
    for i in range(1, len(base) + 1):
        jx.push(h, base[:i])

    # reference: separate clone + push
    ref_handles = jx.clone_many([h, h])
    ref_stats = jx.push_many(
        [(ref_handles[0], base + bytes([0])), (ref_handles[1], base + bytes([1]))]
    )

    # fused: two pushed clones, one clone-only, one in-place push on a
    # throwaway clone of h
    inp = jx.clone(h)
    out = jx.clone_push_many(
        [
            (h, base + bytes([0]), False),
            (h, base + bytes([1]), False),
            (h, None, False),
            (inp, base + bytes([2]), True),
        ]
    )
    assert out[2][1] is None  # clone-only: no stats
    assert out[3][0] == inp  # in-place reuses the handle
    for k in range(2):
        assert_stats_equal(ref_stats[k], out[k][1], f"fused[{k}]")
    # the clone-only copy and the source are indistinguishable
    assert_stats_equal(
        jx.stats(h, base), jx.stats(out[2][0], base), "clone-only"
    )
    # in-place pushed state equals a fresh clone pushed the same way
    ref2 = jx.clone(h)
    ref2_stats = jx.push(ref2, base + bytes([2]))
    assert_stats_equal(ref2_stats, out[3][1], "in-place")


def test_run_extend_forced_first_symbol():
    """A forced first symbol commits without vote checks and matches the
    unforced clone+push route; a node that would lose the next pop still
    commits exactly the forced step."""
    rng = np.random.default_rng(12)
    reads = [bytes(rng.integers(0, 4, size=60)) for _ in range(4)]
    config = CdwfaConfig(min_count=2)
    jx = JaxScorer(reads, config)
    h = jx.root(np.ones(4, dtype=bool))
    st = jx.stats(h, b"")
    # nominate host-side: the strongest next symbol
    votes = (st.occ.astype(float) / np.maximum(st.split, 1)[:, None]).sum(0)
    sym_dense = int(np.argmax(votes))
    sym = int(jx.symtab[sym_dense])

    ref = jx.clone(h)
    ref_stats = jx.push(ref, bytes([sym]))

    # losing node: other_cost 0 stops the run right after the forced step
    steps, code, appended, stats, _recs = jx.run_extend(
        h, b"", 2**31 - 1, 0, 0, 2, False, 64, first_sym=sym_dense
    )
    assert steps == 1
    assert code == 3
    assert appended == bytes([sym])
    assert_stats_equal(ref_stats, stats, "forced")


def test_run_and_push_bundle_finalized_distances():
    """stats.fin from runs and pushes equals finalized_eds at the same
    position."""
    rng = np.random.default_rng(13)
    reads = [bytes(rng.integers(0, 4, size=50)) for _ in range(4)]
    config = CdwfaConfig(min_count=2)
    jx = JaxScorer(reads, config)
    h = jx.root(np.ones(4, dtype=bool))
    steps, code, appended, stats, _recs = jx.run_extend(
        h, b"", 2**31 - 1, 2**31 - 1, 0, 2, False, 500
    )
    assert steps > 0
    if stats.fin is not None:
        np.testing.assert_array_equal(
            stats.fin, jx.finalized_eds(h, appended), "run fin"
        )
    child = jx.clone_push_many([(h, appended + bytes([0]), False)])
    ch, cstats = child[0]
    if cstats.fin is not None:
        np.testing.assert_array_equal(
            cstats.fin, jx.finalized_eds(ch, appended + bytes([0])), "push fin"
        )
