"""Multi-device sharding tests on the virtual 8-CPU mesh: the sharded
consensus step must agree exactly with the single-device kernels, and the
driver entry points must compile and run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from waffle_con_tpu.ops.jax_scorer import NEG, _stats_row, _update_row
from waffle_con_tpu.parallel import (
    make_mesh,
    sharded_branch_step,
    sharded_consensus_step,
)


def needs_devices(n):
    return pytest.mark.skipif(
        len(jax.devices()) < n, reason=f"needs {n} devices"
    )


def _problem(B, R, W, L, seed=0):
    rng = np.random.default_rng(seed)
    reads = jnp.asarray(rng.integers(0, 4, size=(R, L)), dtype=jnp.int32)
    rlen = jnp.full((R,), L, dtype=jnp.int32)
    d = jnp.full((B, R, W), NEG, dtype=jnp.int32).at[:, :, W // 2].set(0)
    e = jnp.zeros((B, R), dtype=jnp.int32)
    off = jnp.zeros((B, R), dtype=jnp.int32)
    act = jnp.ones((B, R), dtype=bool)
    cons = jnp.zeros((B, 64), dtype=jnp.int32)
    clen = jnp.zeros((B,), dtype=jnp.int32)
    return reads, rlen, d, e, off, act, cons, clen


def _reference_step(d, e, off, act, cons, clen, reads, rlen, sym):
    W = d.shape[1]
    emax = jnp.int32(W // 2)
    kvec = jnp.arange(W, dtype=jnp.int32) - W // 2
    cons2 = cons.at[jnp.clip(clen, 0, cons.shape[0] - 1)].set(sym)
    clen2 = clen + 1
    d2, e2, ovf = _update_row(
        d, e, off, act, cons2, clen2, reads, rlen,
        jnp.int32(-2), jnp.bool_(False), kvec, emax,
    )
    eds, occ, _split, reached = _stats_row(
        d2, e2, off, act, cons2, clen2, reads, rlen, 32, kvec
    )
    votes = (occ > 0).sum(axis=0)
    total = jnp.where(act, eds, 0).sum()
    return d2, e2, votes, total, reached.any()


@needs_devices(8)
def test_sharded_consensus_step_matches_single_device():
    mesh = make_mesh(8, axis_names=("read",))
    step = sharded_consensus_step(mesh)
    reads, rlen, d, e, off, act, cons, clen = _problem(1, 16, 17, 24)
    sym = jnp.int32(2)

    d2, e2, votes, total, reached, overflow = step(
        d[0], e[0], off[0], act[0], cons[0], clen[0], reads, rlen, sym,
        jnp.int32(-2), jnp.bool_(False),
    )
    rd, re_, rvotes, rtotal, rreached = _reference_step(
        d[0], e[0], off[0], act[0], cons[0], clen[0], reads, rlen, sym
    )
    np.testing.assert_array_equal(np.asarray(d2), np.asarray(rd))
    np.testing.assert_array_equal(np.asarray(e2), np.asarray(re_))
    np.testing.assert_array_equal(np.asarray(votes), np.asarray(rvotes))
    assert int(total) == int(rtotal)
    assert bool(reached) == bool(rreached)
    assert not bool(overflow)


@needs_devices(8)
def test_sharded_branch_step_matches_single_device():
    mesh = make_mesh(8, shape=(2, 4), axis_names=("branch", "read"))
    step = sharded_branch_step(mesh)
    reads, rlen, d, e, off, act, cons, clen = _problem(4, 8, 17, 24, seed=2)
    syms = jnp.asarray([0, 1, 2, 3], dtype=jnp.int32)

    d2, e2, votes, total, reached, overflow = step(
        d, e, off, act, cons, clen, reads, rlen, syms,
        jnp.int32(-2), jnp.bool_(False),
    )
    for b in range(4):
        rd, re_, rvotes, rtotal, rreached = _reference_step(
            d[b], e[b], off[b], act[b], cons[b], clen[b], reads, rlen, syms[b]
        )
        np.testing.assert_array_equal(np.asarray(d2[b]), np.asarray(rd))
        np.testing.assert_array_equal(np.asarray(e2[b]), np.asarray(re_))
        np.testing.assert_array_equal(np.asarray(votes[b]), np.asarray(rvotes))
        assert int(total[b]) == int(rtotal)
        assert bool(reached[b]) == bool(rreached)
    assert not bool(overflow)


@needs_devices(8)
def test_graft_entry_dryrun():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    mod.dryrun_multichip(8)
    mod.dryrun_multichip(4)
    mod.dryrun_multichip(1)
