"""Multi-device sharding tests on the virtual 8-CPU mesh: the sharded
column step must agree exactly with the single-device kernels, engines
must run end-to-end through a read-sharded scorer with byte-identical
results, and the driver entry points must compile and run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from waffle_con_tpu import CdwfaConfigBuilder, ConsensusDWFA, DualConsensusDWFA
from waffle_con_tpu.ops.jax_scorer import _col_step, _init_col, _stats_core
from waffle_con_tpu.parallel import make_mesh, sharded_col_step
from waffle_con_tpu.utils.example_gen import generate_test


def needs_devices(n):
    return pytest.mark.skipif(
        len(jax.devices()) < n, reason=f"needs {n} devices"
    )


def _problem(R, W, L, seed=0):
    rng = np.random.default_rng(seed)
    reads = jnp.asarray(rng.integers(0, 4, size=(R, L)), dtype=jnp.int32)
    rlen = jnp.full((R,), L, dtype=jnp.int32)
    off = jnp.zeros((R,), dtype=jnp.int32)
    act = jnp.ones((R,), dtype=bool)
    E = jnp.int32((W - 2) // 2)
    D, e, rmin, er = _init_col(off, act, rlen, E, W)
    cons = jnp.zeros((64,), dtype=jnp.int32)
    clen = jnp.int32(0)
    return reads, rlen, D, e, rmin, er, off, act, cons, clen


def _reference_step(D, e, rmin, er, off, act, cons, clen, reads, rlen, sym):
    W = D.shape[1]
    E = jnp.int32((W - 2) // 2)
    cons2 = cons.at[jnp.clip(clen, 0, cons.shape[0] - 1)].set(sym)
    clen2 = clen + 1
    D2, e2, rmin2, er2 = _col_step(
        D, e, rmin, er, off, act, rlen, reads, clen2, sym,
        jnp.int32(-2), jnp.bool_(False), E,
    )
    eds, occ, split, reached = _stats_core(
        D2, e2, rmin2, er2, off, act, rlen, reads, clen2, 32, E
    )
    total = jnp.where(act, eds, 0).sum()
    return D2, e2, rmin2, er2, occ, split, total, reached.any()


@needs_devices(8)
def test_sharded_col_step_matches_single_device():
    mesh = make_mesh(8, axis_names=("read",))
    step = sharded_col_step(mesh)
    reads, rlen, D, e, rmin, er, off, act, cons, clen = _problem(16, 18, 24)
    sym = jnp.int32(2)

    out = step(
        D, e, rmin, er, off, act, cons, clen, reads, rlen, sym,
        jnp.int32(-2), jnp.bool_(False),
    )
    ref = _reference_step(
        D, e, rmin, er, off, act, cons, clen, reads, rlen, sym
    )
    names = ["D", "e", "rmin", "er", "occ", "split"]
    for name, got, want in zip(names, out[:6], ref[:6]):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want), err_msg=name
        )
    assert int(out[6]) == int(ref[6])
    assert bool(out[7]) == bool(ref[7])
    assert not bool(out[8])


@needs_devices(8)
def test_engine_through_sharded_scorer_single():
    """ConsensusDWFA end-to-end on an 8-device read-sharded scorer
    (selected purely via config), byte-identical to the python oracle."""
    truth, reads = generate_test(4, 60, 8, 0.02, seed=11)

    expected = ConsensusDWFA(
        CdwfaConfigBuilder().min_count(2).backend("python").build()
    )
    for r in reads:
        expected.add_sequence(r)
    want = expected.consensus()

    engine = ConsensusDWFA(
        CdwfaConfigBuilder().min_count(2).backend("jax").mesh_shards(8).build()
    )
    for r in reads:
        engine.add_sequence(r)
    got = engine.consensus()
    assert got == want
    assert got[0].sequence == truth


@needs_devices(8)
def test_engine_through_sharded_scorer_dual():
    """DualConsensusDWFA through the sharded scorer: haplotype split with
    exact per-read vote parity."""
    sequences = [b"ACGTACGT", b"ACGTACGT", b"AGGTACGT", b"AGGTACGT"] * 2

    expected = DualConsensusDWFA(
        CdwfaConfigBuilder().min_count(1).backend("python").build()
    )
    for s in sequences:
        expected.add_sequence(s)
    want = expected.consensus()

    engine = DualConsensusDWFA(
        CdwfaConfigBuilder()
        .min_count(1)
        .backend("jax")
        .mesh_shards(8)
        .build()
    )
    for s in sequences:
        engine.add_sequence(s)
    got = engine.consensus()
    assert got == want


@needs_devices(8)
def test_engine_through_sharded_scorer_priority():
    """PriorityConsensusDWFA through the mesh: every worklist group is a
    SubsetScorer view over ONE sharded base scorer per chain level (the
    subset is just the root activation mask on mesh-sharded state), with
    recursive splits byte-identical to the python oracle."""
    from waffle_con_tpu import PriorityConsensusDWFA

    chains = [
        [b"ACGTACGT", b"ACGTACGTTT"],
        [b"ACGTACGT", b"ACGTACGTTT"],
        [b"ACGTACGT", b"ACTTACGTAA"],
        [b"ACGTACGT", b"ACTTACGTAA"],
    ] * 2

    expected = PriorityConsensusDWFA(
        CdwfaConfigBuilder().min_count(1).backend("python").build()
    )
    for ch in chains:
        expected.add_sequence_chain(ch)
    want = expected.consensus()

    engine = PriorityConsensusDWFA(
        CdwfaConfigBuilder()
        .min_count(1)
        .backend("jax")
        .mesh_shards(8)
        .build()
    )
    for ch in chains:
        engine.add_sequence_chain(ch)
    got = engine.consensus()
    assert got == want
    assert len(got.consensuses) == 2


@needs_devices(8)
@pytest.mark.slow
def test_sharded_priority_scale():
    """RUN_SLOW tier: the priority engine through the 8-device mesh at
    >= 2 kb reads (VERDICT r4 weak #4 — sharded paths beyond toy scale),
    vs the native C++ engine."""
    from waffle_con_tpu import PriorityConsensusDWFA
    from waffle_con_tpu.native import native_priority_consensus
    from waffle_con_tpu.utils.example_gen import corrupt

    num_reads, seq_len, er = 16, 2000, 0.01
    truth, level0 = generate_test(4, seq_len // 2, num_reads, er, seed=3)
    t1a, _ = generate_test(4, seq_len, 1, 0.0, seed=4)
    t1b = bytearray(t1a)
    t1b[seq_len // 3] = (t1b[seq_len // 3] + 1) % 4
    t1b[2 * seq_len // 3] = (t1b[2 * seq_len // 3] + 2) % 4
    t1b = bytes(t1b)
    chains = []
    for i in range(num_reads):
        lvl1_truth = t1a if i < num_reads // 2 else t1b
        lvl1 = corrupt(lvl1_truth, er, np.random.default_rng(200 + i))
        chains.append([level0[i], lvl1])

    band = 16 + int(2 * er * seq_len)
    cfg = lambda b: (  # noqa: E731
        CdwfaConfigBuilder()
        .min_count(max(2, num_reads // 4))
        .backend(b)
        .initial_band(band)
        .mesh_shards(8 if b == "jax" else 0)
        .build()
    )
    want = native_priority_consensus(chains, config=cfg("native"))
    engine = PriorityConsensusDWFA(cfg("jax"))
    for ch in chains:
        engine.add_sequence_chain(ch)
    assert engine.consensus() == want


@needs_devices(8)
def test_graft_entry_dryrun():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    mod.dryrun_multichip(8)


def test_shard_scorer_rejects_unknown_axis():
    """Axis-name validation (ADVICE r2): a mesh without the requested
    read axis must fail loudly, not silently shard over all devices."""
    from waffle_con_tpu.ops.jax_scorer import JaxScorer
    from waffle_con_tpu.parallel import make_mesh
    from waffle_con_tpu.parallel.mesh import shard_scorer

    cfg = CdwfaConfigBuilder().backend("jax").build()
    jx = JaxScorer([b"ACGT"] * 8, cfg)
    mesh = make_mesh(2, axis_names=("data",))
    with pytest.raises(ValueError, match="no axis 'read'"):
        shard_scorer(jx, mesh)


# ----------------------------------------- device topology (scale-out)


def test_probe_device_count_caches_the_probe(monkeypatch):
    from waffle_con_tpu.parallel import mesh

    mesh.reset_probe_cache()
    real = jax.devices
    calls = []

    def counting(*a):
        calls.append(1)
        return real(*a)

    monkeypatch.setattr(jax, "devices", counting)
    try:
        n1 = mesh.probe_device_count()
        n2 = mesh.probe_device_count()
    finally:
        mesh.reset_probe_cache()
    assert n1 == n2 == len(real())
    # the whole point: one backend probe per process, not per job
    assert len(calls) == 1


def test_device_slices_partitions_disjointly():
    from waffle_con_tpu.parallel.mesh import device_slices

    devs = [f"dev{i}" for i in range(8)]
    slices = device_slices(3, devices=devs, name_prefix="rep")
    assert [s.name for s in slices] == ["rep0", "rep1", "rep2"]
    assert [len(s) for s in slices] == [3, 3, 2]
    flat = [d for s in slices for d in s.devices]
    assert flat == devs  # contiguous, disjoint, complete


def test_device_slices_round_robin_when_oversubscribed():
    from waffle_con_tpu.parallel.mesh import device_slices

    devs = ["dev0", "dev1"]
    slices = device_slices(4, devices=devs)
    assert [s.devices for s in slices] == [
        ("dev0",), ("dev1",), ("dev0",), ("dev1",),
    ]
    with pytest.raises(ValueError, match="n_slices"):
        device_slices(0, devices=devs)


def test_device_set_rejects_empty():
    from waffle_con_tpu.parallel.mesh import DeviceSet

    with pytest.raises(ValueError, match="empty"):
        DeviceSet("none", ())


def test_use_device_set_is_nested_and_thread_scoped():
    from waffle_con_tpu.parallel.mesh import (
        DeviceSet,
        current_device_set,
        use_device_set,
    )

    outer = DeviceSet("outer", ("dev0",))
    inner = DeviceSet("inner", ("dev1",))
    assert current_device_set() is None
    with use_device_set(outer):
        assert current_device_set() is outer
        with use_device_set(inner):
            assert current_device_set() is inner
        assert current_device_set() is outer
    assert current_device_set() is None

    import threading

    seen = []
    with use_device_set(outer):
        t = threading.Thread(
            target=lambda: seen.append(current_device_set())
        )
        t.start()
        t.join()
    assert seen == [None]  # the pin is thread-local, not process-global


@needs_devices(4)
def test_make_mesh_draws_from_pinned_device_set():
    from waffle_con_tpu.parallel.mesh import DeviceSet, use_device_set

    devs = jax.devices()
    pinned = DeviceSet("pin", tuple(devs[:2]))
    with use_device_set(pinned):
        mesh = make_mesh(axis_names=("read",))
        assert mesh.devices.size == 2
        # an explicit devices argument overrides the thread pin
        mesh = make_mesh(devices=devs[:4], axis_names=("read",))
        assert mesh.devices.size == 4
    # outside the scope the full topology is back
    assert make_mesh(axis_names=("read",)).devices.size == len(devs)


@needs_devices(2)
def test_shard_for_config_fails_fast_without_touching_the_scorer():
    from waffle_con_tpu.parallel.mesh import (
        DeviceSet,
        shard_for_config,
        use_device_set,
    )

    cfg = CdwfaConfigBuilder().backend("jax").mesh_shards(4).build()
    tiny = DeviceSet("tiny", tuple(jax.devices()[:2]))
    with use_device_set(tiny):
        # scorer=None proves the availability check runs first: an
        # over-asking config must fail before any state is built
        with pytest.raises(ValueError, match="exceeds the 2 available"):
            shard_for_config(None, cfg)
    # unsharded configs are a no-op regardless of scorer
    shard_for_config(None, CdwfaConfigBuilder().backend("jax").build())
