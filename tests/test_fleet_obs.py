"""Fleet observability plane: cross-process distributed tracing,
federated metrics, and incident aggregation for the proc fleet.

Layers under test:

* **wire** — the SUBMIT ``trace`` context codec (valid dicts decode to
  exactly the normalized shape; malformed ones raise typed
  ``WireError``; a 300-mutation fuzz of SUBMIT-with-trace frames never
  raises anything untyped), and the obs-side
  ``context_to_wire``/``context_from_wire`` round trip.
* **door, fake workers** — STATS frames merge into the door registry
  as ``worker=``-labeled series in one ``render_prometheus()``
  exposition; INCIDENT frames land exactly once in the door's
  ``WAFFLE_FLIGHT_DIR`` under fleet-level ``(reason, trace_id)``
  dedupe with worker attribution; with tracing/metrics disabled the
  SUBMIT payload carries **no** ``trace`` key at all (frames absent,
  not empty).
* **real subprocess** — one served job yields one *connected* span
  tree containing both door-side spans (``door:job``/``door:queued``)
  and worker-side spans (``serve:job``/``search``) under the same
  trace id and Chrome pid, stitched by flow arrows across the socket
  hop; with the plane disarmed a real worker sends zero STATS frames
  and returns zero span events.
"""

import json
import os
import random
import socket
import threading
import time

import pytest

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.models.consensus import Consensus
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import slo as obs_slo
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.serve import (
    JobRequest,
    ProcConfig,
    ProcFrontDoor,
)
from waffle_con_tpu.serve.procs import wire

pytestmark = pytest.mark.serve


# ---------------------------------------------------------------------
# trace-context wire codec
# ---------------------------------------------------------------------

def test_trace_context_wire_roundtrip():
    ctx = obs_trace.TraceContext("storm/job-3", 1_000_003,
                                 label="job-3 [tag]")
    obj = obs_trace.context_to_wire(ctx, parent_span_id=1,
                                    span_base=2_000_000, flow_id=48)
    decoded = wire.decode_trace(obj)
    assert decoded == {
        "trace_id": "storm/job-3",
        "chrome_pid": 1_000_003,
        "label": "job-3 [tag]",
        "parent_span_id": 1,
        "span_base": 2_000_000,
        "flow_id": 48,
    }
    adopted = obs_trace.context_from_wire(decoded)
    assert adopted.trace_id == ctx.trace_id
    assert adopted.chrome_pid == ctx.chrome_pid
    assert adopted.root_parent == 1
    # adopted span ids start above the disjoint base, parenting the
    # first stack-root span under the door's per-job root
    span_id, parent = adopted._open_span()
    assert span_id == 2_000_001
    assert parent == 1


def test_decode_trace_optional_and_malformed():
    assert wire.decode_trace(None) is None
    minimal = wire.decode_trace(
        {"trace_id": "t", "chrome_pid": 5}
    )
    assert minimal["span_base"] == 0
    assert minimal["parent_span_id"] is None
    assert minimal["flow_id"] is None
    for bad in (
        [],                                    # not a dict
        {"chrome_pid": 5},                     # missing trace_id
        {"trace_id": "t"},                     # missing chrome_pid
        {"trace_id": "t", "chrome_pid": "x"},  # non-int pid
        {"trace_id": "t", "chrome_pid": -1},   # negative pid
        {"trace_id": "t", "chrome_pid": 5, "span_base": -2},
        {"trace_id": "t", "chrome_pid": 5, "parent_span_id": "n"},
    ):
        with pytest.raises(wire.WireError):
            wire.decode_trace(bad)


def test_submit_with_trace_fuzz_never_untyped(monkeypatch):
    # mutated SUBMIT frames carrying the new trace fields must always
    # either decode cleanly or raise a typed WireError — and when they
    # decode, decode_trace on the (possibly mangled) trace dict must
    # itself stay typed
    monkeypatch.setenv("WAFFLE_PROC_FRAME_MAX", "65536")
    ctx = obs_trace.TraceContext("fuzz/job-1", 1_000_001)
    base = wire.encode_frame(wire.FrameType.SUBMIT, {
        "job": 1,
        "request": {"kind": "single", "reads": ["QUNHVA=="]},
        "trace": obs_trace.context_to_wire(
            ctx, parent_span_id=1, span_base=1_000_000, flow_id=16
        ),
    })
    rng = random.Random(20260806)
    for _ in range(300):
        blob = bytearray(base)
        for _ in range(rng.randint(1, 4)):
            blob[rng.randrange(len(blob))] = rng.randrange(256)
        try:
            frames = wire.FrameDecoder().feed(bytes(blob))
        except wire.WireError:
            continue
        for _ftype, obj in frames:
            if not isinstance(obj, dict):
                continue
            try:
                wire.decode_trace(obj.get("trace"))
            except wire.WireError:
                pass


# ---------------------------------------------------------------------
# federated metrics: registry merge + door-level STATS
# ---------------------------------------------------------------------

@pytest.fixture
def metrics_on():
    obs_metrics.enable_metrics(True)
    obs_metrics.registry().reset()
    try:
        yield obs_metrics.registry()
    finally:
        obs_metrics.registry().reset()
        obs_metrics.reset_metrics_enabled()


def test_merge_snapshot_relabels_series(metrics_on):
    reg = metrics_on
    snap = {
        "waffle_searches_total": {
            "type": "counter",
            "series": {'{backend="python"}': 4.0, "{}": 2.0},
        },
        "waffle_serve_active_jobs": {
            "type": "gauge", "series": {"{}": 3.0},
        },
        "waffle_dispatch_latency_seconds": {
            "type": "histogram",
            "series": {'{op="run"}': {
                "buckets": {"0.01": 2, "0.1": 5}, "overflow": 1,
                "sum": 0.4, "count": 8,
            }},
        },
    }
    assert reg.merge_snapshot(snap, worker="s:w0") == 4
    text = reg.render_prometheus()
    assert 'waffle_searches_total{backend="python",worker="s:w0"} 4.0' \
        in text
    assert 'waffle_serve_active_jobs{worker="s:w0"} 3.0' in text
    assert 'waffle_dispatch_latency_seconds_count{op="run",worker="s:w0"}' \
        " 8" in text
    # re-merging a newer snapshot SETS the value (no double counting)
    snap["waffle_searches_total"]["series"]['{backend="python"}'] = 6.0
    reg.merge_snapshot(snap, worker="s:w0")
    assert 'backend="python",worker="s:w0"} 6.0' \
        in reg.render_prometheus()


def test_merge_snapshot_skips_malformed_series(metrics_on):
    reg = metrics_on
    reg.counter("waffle_fleet_clash_total").inc()
    merged = reg.merge_snapshot({
        "not_a_family": "bogus",
        "waffle_fleet_clash_total": {            # kind collision
            "type": "gauge", "series": {"{}": 1.0},
        },
        "waffle_bad_value": {
            "type": "counter", "series": {"{}": "NaNsense?"},
        },
        "waffle_good": {"type": "counter", "series": {"{}": 2.0}},
    }, worker="w")
    assert merged == 1
    assert 'waffle_good{worker="w"} 2.0' in reg.render_prometheus()


class _ObsWorker:
    """Minimal scripted worker for the fleet-obs door paths: HELLO,
    answers SUBMIT, captures every SUBMIT payload, and sends whatever
    STATS/INCIDENT frames the test scripts via :meth:`send`."""

    def __init__(self, socket_path, name, spec):
        self.name = name
        self.spec = json.loads(spec)
        self.submits = []
        self.pid = os.getpid()
        self._sock = None
        self._connected = threading.Event()
        self._exited = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(socket_path,), daemon=True
        )
        self._thread.start()

    def poll(self):
        return None if not self._exited.is_set() else 0

    def wait(self, timeout=None):
        self._exited.wait(timeout)
        return 0

    def terminate(self):
        self._exited.set()

    kill = terminate

    def send(self, ftype, obj):
        assert self._connected.wait(5)
        self._sock.sendall(wire.encode_frame(ftype, obj))

    def _run(self, socket_path):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(socket_path)
        self._sock = sock
        decoder = wire.FrameDecoder()
        sock.sendall(wire.encode_frame(wire.FrameType.HELLO, {
            "worker": self.name, "pid": self.pid, "slots": 2,
        }))
        self._connected.set()
        try:
            while True:
                data = sock.recv(65536)
                if not data:
                    return
                for ftype, obj in decoder.feed(data):
                    if ftype is wire.FrameType.PING:
                        sock.sendall(wire.encode_frame(
                            wire.FrameType.PONG, {"outstanding": 0},
                        ))
                    elif ftype is wire.FrameType.SUBMIT:
                        self.submits.append(obj)
                        result = [Consensus(
                            b"FAKE", ConsensusCost.L1_DISTANCE, [0, 0]
                        )]
                        sock.sendall(wire.encode_frame(
                            wire.FrameType.STARTED, {"job": obj["job"]}
                        ))
                        sock.sendall(wire.encode_frame(
                            wire.FrameType.RESULT, {
                                "job": obj["job"], "kind": "single",
                                "result": wire.encode_result(
                                    "single", result
                                ),
                            }
                        ))
                    elif ftype is wire.FrameType.SHUTDOWN:
                        return
        except OSError:
            pass
        finally:
            self._exited.set()
            try:
                sock.close()
            except OSError:
                pass


class _ObsFleet:
    def __init__(self):
        self.workers = {}

    def __call__(self, socket_path, name, spec):
        worker = _ObsWorker(socket_path, name, spec)
        self.workers[name] = worker
        return worker


def _request():
    return JobRequest(kind="single", reads=(b"ACGT", b"ACGT"),
                      config=CdwfaConfig())


def _door(fleet, **kw):
    kw.setdefault("workers", 2)
    kw.setdefault("name", "fleet")
    kw.setdefault("spawn_timeout_s", 10.0)
    return ProcFrontDoor(ProcConfig(launcher=fleet, **kw))


def test_stats_frame_merges_as_worker_labeled_series(metrics_on):
    fleet = _ObsFleet()
    with _door(fleet) as door:
        door.submit(_request()).result(timeout=10)
        for name, worker in fleet.workers.items():
            worker.send(wire.FrameType.STATS, {
                "worker": name,
                "unix_time": time.time(),
                "metrics": {
                    "waffle_searches_total": {
                        "type": "counter", "series": {"{}": 5.0},
                    },
                },
                "slo": {"dispatch": {"count": 5, "p95_s": 0.025}},
                "incidents": 0,
            })
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            rows = door.worker_stats()
            if all(w["stats_frames"] == 1 for w in rows):
                break
            time.sleep(0.01)
        rows = {w["worker"]: w for w in door.worker_stats()}
        stats = door.stats()
    assert all(w["stats_frames"] == 1 for w in rows.values()), rows
    assert all(w["stats_at"] is not None for w in rows.values())
    assert all(w["dispatch_p95_s"] == 0.025 for w in rows.values())
    assert stats["fleet"]["stats_frames"] == 2
    # one exposition, one series per worker
    text = metrics_on.render_prometheus()
    for name in rows:
        assert f'waffle_searches_total{{worker="{name}"}} 5.0' in text
    # the workers' spec told them to arm metrics
    assert all(w.spec["metrics"] for w in fleet.workers.values())


def test_forwarded_incident_dumped_once_with_attribution(
        tmp_path, monkeypatch, metrics_on):
    monkeypatch.setenv("WAFFLE_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("WAFFLE_FLIGHT_DEDUPE_S", "60")
    obs_flight.reset()
    incident = {
        "schema": "waffle-flight-incident/1",
        "seq": 7,
        "reason": "backend_demoted",
        "trace_id": "fleet/job-1",
        "unix_time": time.time(),
        "detail": {"why": "injected"},
        "path": "/worker/side/incident-000007.json",
    }
    fleet = _ObsFleet()
    try:
        with _door(fleet, workers=1) as door:
            worker = fleet.workers["fleet:w0"]
            for _ in range(2):  # same (reason, trace_id): fleet dedupe
                worker.send(wire.FrameType.INCIDENT, {
                    "worker": worker.name, "incident": dict(incident),
                })
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if door.worker_stats()[0]["incidents"] == 2:
                    break
                time.sleep(0.01)
            stats = door.stats()
        assert stats["fleet"]["incidents_forwarded"] == 2
        dumps = sorted(tmp_path.glob("incident-*.json"))
        assert len(dumps) == 1, [d.name for d in dumps]
        dumped = json.loads(dumps[0].read_text())
        assert dumped["reason"] == "backend_demoted"
        assert dumped["worker"] == "fleet:w0"
        assert dumped["origin"] == "remote"
        assert dumped["trace_id"] == "fleet/job-1"
        # the worker-side dump path is preserved, not clobbered by the
        # door's own
        assert dumped["worker_path"] == incident["path"]
        # door-side recorder kept it in memory with its own dump path
        kept = obs_flight.incidents()
        assert [i["reason"] for i in kept] == ["backend_demoted"]
        assert kept[0]["path"] == str(dumps[0])
    finally:
        obs_flight.reset()


def test_unknown_incident_payload_is_ignored():
    obs_flight.reset()
    fleet = _ObsFleet()
    try:
        with _door(fleet, workers=1) as door:
            worker = fleet.workers["fleet:w0"]
            worker.send(wire.FrameType.INCIDENT, {"incident": "nope"})
            worker.send(wire.FrameType.STATS, ["not", "a", "dict"])
            door.submit(_request()).result(timeout=10)
            stats = door.stats()
        assert stats["fleet"]["incidents_forwarded"] == 0
        assert stats["fleet"]["stats_frames"] == 0
        assert obs_flight.incidents() == []
    finally:
        obs_flight.reset()


# ---------------------------------------------------------------------
# zero overhead when the plane is disarmed
# ---------------------------------------------------------------------

def test_submit_carries_no_trace_when_tracing_disabled():
    assert not obs_trace.tracing_enabled()
    assert not obs_metrics.metrics_enabled()
    fleet = _ObsFleet()
    with _door(fleet) as door:
        handles = [door.submit(_request()) for _ in range(4)]
        for h in handles:
            h.result(timeout=10)
        stats = door.stats()
    submits = [obj for w in fleet.workers.values() for obj in w.submits]
    assert len(submits) == 4
    # the key is absent, not present-but-empty
    assert all("trace" not in obj for obj in submits)
    # and the spec told the workers to keep their plane disarmed too
    assert all(not w.spec["trace"] and not w.spec["metrics"]
               for w in fleet.workers.values())
    assert stats["fleet"] == {
        "stats_frames": 0, "incidents_forwarded": 0, "span_events": 0,
    }


def test_real_worker_sends_no_stats_frames_when_disabled(monkeypatch):
    # a real subprocess worker with the plane disarmed: even with an
    # aggressive STATS cadence configured, no STATS frame ever arrives
    # and no span buffer rides the RESULT frames
    monkeypatch.setenv("WAFFLE_PROC_STATS_S", "0.1")
    assert not obs_trace.tracing_enabled()
    assert not obs_metrics.metrics_enabled()
    cfg = CdwfaConfig(backend="python", min_count=2)
    req = JobRequest(kind="single", reads=(b"ACGTACGTAC",) * 3,
                     config=cfg)
    with ProcFrontDoor(ProcConfig(workers=1, name="dark")) as door:
        door.submit(req).result(timeout=60)
        time.sleep(0.5)  # several would-be STATS periods
        stats = door.stats()
    assert stats["fleet"] == {
        "stats_frames": 0, "incidents_forwarded": 0, "span_events": 0,
    }


# ---------------------------------------------------------------------
# real subprocess: one connected cross-process trace
# ---------------------------------------------------------------------

def _span_index(spans):
    return {e["args"]["span_id"]: e for e in spans}


def test_subprocess_job_yields_one_connected_cross_process_tree():
    tracer = obs_trace.get_tracer()
    tracer.enable(True)
    tracer.clear()
    obs_metrics.enable_metrics(True)
    obs_slo.reset()
    try:
        cfg = CdwfaConfig(backend="python", min_count=2)
        req = JobRequest(kind="single", reads=(b"ACGTACGTAC",) * 3,
                         config=cfg)
        with ProcFrontDoor(ProcConfig(workers=1, name="e2e")) as door:
            handle = door.submit(req)
            handle.result(timeout=60)
            stats = door.stats()
        assert stats["fleet"]["span_events"] > 0

        events = tracer.chrome_events()
        pid = handle.trace.chrome_pid
        trace_id = handle.trace.trace_id
        spans = [
            e for e in events
            if e.get("ph") == "X"
            and e.get("args", {}).get("trace_id") == trace_id
        ]
        names = {e["name"] for e in spans}
        # door-side and worker-side phases on the same timeline
        assert {"door:job", "door:queued", "serve:job", "search"} <= \
            names, names
        # every span renders under the job's own Chrome pid
        assert {e["pid"] for e in spans} == {pid}
        # worker-origin spans carry attribution; door-origin ones don't
        origins = {bool(e["args"].get("worker")) for e in spans}
        assert origins == {True, False}
        # parent linkage is closed and single-rooted at door:job
        by_id = _span_index(spans)
        roots = [e for e in spans if e["args"]["parent_id"] is None]
        assert [e["name"] for e in roots] == ["door:job"]
        for e in spans:
            parent = e["args"]["parent_id"]
            assert parent is None or parent in by_id, e
        # the worker's serve:job parents directly under the door root
        serve_job = next(e for e in spans if e["name"] == "serve:job")
        assert serve_job["args"]["parent_id"] == \
            roots[0]["args"]["span_id"]
        # flow arrows stitch the socket hop: both directions, and every
        # finish has a matching start id
        flows = [e for e in events
                 if e.get("cat") == "flow" and e.get("pid") == pid]
        starts = {e["id"] for e in flows if e["ph"] == "s"}
        finishes = {e["id"] for e in flows if e["ph"] == "f"}
        assert starts and finishes
        assert finishes <= starts
        assert len(starts & finishes) >= 2  # submit hop + result hop
    finally:
        tracer.reset_enabled()
        tracer.clear()
        obs_metrics.registry().reset()
        obs_metrics.reset_metrics_enabled()
        obs_slo.reset()
