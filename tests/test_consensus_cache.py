"""Content-addressed consensus cache (``serve/cache``).

Canonical-hash properties (the satellite contract): read-order
permutation invariance, duplicate-read multiplicity sensitivity,
scoring-config field sensitivity, placement-only field insensitivity.
Plus the store layer (LRU bounds, file-store hash-sealing and
quarantine), the bound-free checkpoint deposit gate, and the service
integration: exact hits serve ``CACHED`` without touching a worker,
near-miss proposals certify to ``CERTIFIED`` at the optimal cost or
degrade, checkpoint supersets resume — every served byte identical to
the serial reference.
"""

import json
import os

import pytest

from waffle_con_tpu import CdwfaConfigBuilder
from waffle_con_tpu.serve import (
    ConsensusService,
    JobRequest,
    JobStatus,
    ServeConfig,
)
from waffle_con_tpu.serve.cache import (
    ConsensusCache,
    keys,
    resumable_wire,
)
from waffle_con_tpu.serve.cache.store import FileStore, ResultStore
from waffle_con_tpu.serve.service import _build_engine
from waffle_con_tpu.utils.example_gen import generate_test

pytestmark = pytest.mark.serve


def _cfg(backend="python", **kw):
    b = CdwfaConfigBuilder().backend(backend)
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _reads(n=6, seq_len=120, error=0.02, seed=11):
    return tuple(generate_test(4, seq_len, n, error, seed=seed)[1])


def _req(reads, config=None, kind="single", **kw):
    return JobRequest(kind=kind, reads=reads, config=config, **kw)


# ------------------------------------------------- canonical hash


def test_key_invariant_under_read_permutation():
    reads = _reads()
    cfg = _cfg(min_count=2)
    permuted = reads[::-1]
    assert permuted != reads
    assert keys.request_key(_req(reads, cfg)) == \
        keys.request_key(_req(permuted, cfg))


def test_key_sensitive_to_duplicate_multiplicity():
    reads = _reads()
    cfg = _cfg(min_count=2)
    doubled = reads + (reads[0],)
    assert keys.request_key(_req(reads, cfg)) != \
        keys.request_key(_req(doubled, cfg))


def test_key_sensitive_to_scoring_fields():
    reads = _reads()
    base = keys.request_key(_req(reads, _cfg(min_count=2)))
    assert base != keys.request_key(_req(reads, _cfg(min_count=3)))
    assert base != keys.request_key(
        _req(reads, _cfg(min_count=2, wildcard=ord("*")))
    )


def test_key_insensitive_to_placement_fields():
    reads = _reads()
    base = keys.request_key(_req(reads, _cfg(min_count=2)))
    jax_meshed = _cfg(
        backend="jax", min_count=2, mesh_shards=2, initial_band=9,
    )
    assert keys.request_key(_req(reads, jax_meshed)) == base


def test_key_sensitive_to_kind_and_offsets():
    reads = _reads()
    cfg = _cfg(min_count=2)
    base = keys.request_key(_req(reads, cfg))
    assert base != keys.request_key(_req(reads, cfg, kind="dual"))
    seeded = _req(reads, cfg, offsets=(None,) * (len(reads) - 1) + (3,))
    assert base != keys.request_key(seeded)


def test_priority_chains_keep_within_chain_order():
    cfg = _cfg(min_count=2)
    c1, c2 = (b"\x00\x01", b"\x02\x03"), (b"\x01\x02", b"\x03\x00")
    key = keys.request_key(_req((c1, c2), cfg, kind="priority"))
    # chain multiset is order-insensitive ...
    assert key == keys.request_key(_req((c2, c1), cfg, kind="priority"))
    # ... but within-chain order is positional seeding: never collapsed
    flipped = (tuple(reversed(c1)), c2)
    assert key != keys.request_key(_req(flipped, cfg, kind="priority"))


def test_multiset_extras_and_match_permutation():
    reads = _reads()
    extra = b"\x00\x01\x02\x03"
    extras = keys.multiset_extras(reads + (extra,), reads)
    assert extras == (extra,)
    assert keys.multiset_extras(reads[:-1], reads) is None
    # duplicate copies count: one copy is not a superset of two
    assert keys.multiset_extras(reads, reads + (reads[0],)) is None

    stored = keys.read_elements(_req(reads, None))
    wanted = keys.read_elements(_req(reads[::-1], None))
    perm = keys.match_permutation(wanted, stored)
    assert perm is not None
    assert [stored[j] for j in perm] == wanted
    assert keys.match_permutation(
        keys.read_elements(_req(reads + (extra,), None)), stored
    ) is None


# ------------------------------------------------- stores


def test_result_store_is_bounded_lru():
    store = ResultStore(2)
    store.put("a", 1)
    store.put("b", 2)
    assert store.get("a") == 1  # refreshes "a"
    store.put("c", 3)  # evicts "b", the least recently used
    assert store.get("b") is None
    assert store.get("a") == 1 and store.get("c") == 3
    assert len(store) == 2


def test_file_store_round_trip_and_quarantine(tmp_path):
    store = FileStore(str(tmp_path))
    store.put("k1", {"kind": "single", "result": [1, 2]})
    assert store.get("k1") == {"kind": "single", "result": [1, 2]}
    # reopening reads the manifest back
    assert FileStore(str(tmp_path)).get("k1") is not None

    # corrupt the sealed bytes: the digest mismatch quarantines the
    # entry — it is never served again, from this or a fresh store
    victim = next(
        p for p in tmp_path.iterdir()
        if p.is_file() and p.name != "MANIFEST.json"
    )
    victim.write_bytes(victim.read_bytes() + b" ")
    assert store.get("k1") is None
    assert store.quarantined == 1
    assert (tmp_path / "_quarantine").exists()
    assert FileStore(str(tmp_path)).get("k1") is None


# ------------------------------------------------- checkpoint gate


def _wire_ckpt(entries=1, maximum_error=None, results=()):
    return {
        "version": 1, "kind": "single",
        "body": {"state": {
            "entries": [{"n": i} for i in range(entries)],
            "maximum_error": maximum_error,
            "results": list(results),
        }},
    }


def test_resumable_wire_accepts_only_bound_free_frontiers():
    assert resumable_wire(_wire_ckpt())
    # an incumbent bound would prune the superset's optimum with
    # subset-only costs: never resumable
    assert not resumable_wire(_wire_ckpt(maximum_error=7))
    assert not resumable_wire(_wire_ckpt(results=[{"c": 1}]))
    assert not resumable_wire(_wire_ckpt(entries=0))
    assert not resumable_wire({"body": {}})
    assert not resumable_wire(None)


def test_deposit_checkpoint_rejects_bounded_snapshots():
    cache = ConsensusCache("t")
    req = _req(_reads(), _cfg(min_count=2))
    cache.deposit_checkpoint(req, _wire_ckpt(maximum_error=3))
    assert cache.stats()["ckpt_deposits"] == 0
    cache.deposit_checkpoint(req, _wire_ckpt())
    assert cache.stats()["ckpt_deposits"] == 1


# ------------------------------------------------- service integration


def _serial(request):
    return _build_engine(request).consensus()


@pytest.fixture
def cache_env(monkeypatch):
    monkeypatch.setenv("WAFFLE_CACHE", "1")
    return monkeypatch


def test_exact_duplicate_served_cached_and_dispatch_free(cache_env):
    reads = _reads()
    cfg = _cfg(min_count=2)
    dup = _req(reads[::-1], cfg)
    want = _serial(dup)
    with ConsensusService(ServeConfig(workers=2)) as svc:
        first = svc.submit(_req(reads, cfg))
        first.result(timeout=300)
        _wait_deposits(svc, 1)
        second = svc.submit(dup)
        got = second.result(timeout=300)
        stats = svc.stats()
    assert second.status is JobStatus.CACHED
    assert second.started_at is None  # never dispatched
    assert got == want  # scores remapped to the submitted read order
    assert stats["cache"]["exact"] == 1
    assert stats["jobs"]["cached"] == 1


def test_superset_with_cached_consensus_certifies(cache_env):
    reads = _reads()
    cfg = _cfg(min_count=2)
    with ConsensusService(ServeConfig(workers=2)) as svc:
        first = svc.submit(_req(reads, cfg))
        base = first.result(timeout=300)
        _wait_deposits(svc, 1)
        superset = _req(reads + (base[0].sequence,), cfg)
        want = _serial(superset)
        handle = svc.submit(superset)
        got = handle.result(timeout=300)
        stats = svc.stats()
    assert handle.status is JobStatus.CERTIFIED
    assert got == want
    assert stats["cache"]["certified"] == 1


def test_certify_failure_degrades_to_full_search(cache_env):
    reads = _reads()
    cfg = _cfg(min_count=2)
    noisy = generate_test(4, 120, 1, 0.3, seed=99)[1][0]
    with ConsensusService(ServeConfig(workers=2)) as svc:
        svc.submit(_req(reads, cfg)).result(timeout=300)
        _wait_deposits(svc, 1)
        superset = _req(reads + (noisy,), cfg)
        want = _serial(superset)
        handle = svc.submit(superset)
        got = handle.result(timeout=300)
        stats = svc.stats()
    # the noisy extra raises the optimal cost past the cached bound:
    # the proposal fails certification and the job runs a real search
    assert handle.status is JobStatus.DONE
    assert got == want
    assert stats["cache"]["certify_failed"] >= 1


def test_checkpoint_superset_resumes_with_parity(cache_env):
    cache_env.setenv("WAFFLE_CKPT_INTERVAL_S", "0.0001")
    cache_env.setenv("WAFFLE_CACHE_PROPOSALS", "0")  # isolate the tier
    reads = _reads(n=8, seq_len=160, error=0.03, seed=21)
    extra = generate_test(4, 160, 1, 0.05, seed=22)[1][0]
    cfg = _cfg(min_count=2)
    with ConsensusService(ServeConfig(workers=2)) as svc:
        svc.submit(_req(reads, cfg)).result(timeout=300)
        _wait_deposits(svc, 1)
        if svc.stats()["cache"]["ckpt_deposits"] == 0:
            pytest.skip("search finished before a bound-free snapshot")
        superset = _req(reads + (extra,), cfg)
        want = _serial(superset)
        handle = svc.submit(superset)
        got = handle.result(timeout=300)
        stats = svc.stats()
    assert handle.status is JobStatus.DONE
    assert got == want  # bound-free resume is byte-identical
    assert stats["cache"]["checkpoint"] == 1
    assert stats["checkpoints"]["resumed"] >= 1


def test_resumed_jobs_never_deposit(cache_env):
    cache_env.setenv("WAFFLE_CKPT_INTERVAL_S", "0.0001")
    cache_env.setenv("WAFFLE_CACHE_PROPOSALS", "0")
    reads = _reads(n=8, seq_len=160, error=0.03, seed=21)
    extra = generate_test(4, 160, 1, 0.05, seed=22)[1][0]
    cfg = _cfg(min_count=2)
    with ConsensusService(ServeConfig(workers=2)) as svc:
        svc.submit(_req(reads, cfg)).result(timeout=300)
        _wait_deposits(svc, 1)
        if svc.stats()["cache"]["ckpt_deposits"] == 0:
            pytest.skip("search finished before a bound-free snapshot")
        handle = svc.submit(_req(reads + (extra,), cfg))
        handle.result(timeout=300)
        import time

        time.sleep(0.2)  # give a (buggy) late deposit time to land
        # a resumed search did not cover the space from scratch: its
        # result and checkpoints stay out of the cache (fail-closed)
        assert svc.stats()["cache"]["deposits"] == 1


def test_file_store_serves_across_service_restarts(cache_env, tmp_path):
    cache_env.setenv("WAFFLE_CACHE_DIR", str(tmp_path))
    reads = _reads()
    cfg = _cfg(min_count=2)
    with ConsensusService(ServeConfig(workers=2)) as svc:
        want = svc.submit(_req(reads, cfg)).result(timeout=300)
        _wait_deposits(svc, 1)
    with ConsensusService(ServeConfig(workers=2)) as svc:
        handle = svc.submit(_req(reads[::-1], cfg))
        got = handle.result(timeout=300)
        assert handle.status is JobStatus.CACHED
        assert svc.stats()["cache"]["exact"] == 1
    assert [c.sequence for c in got] == [c.sequence for c in want]


def test_cache_off_by_default():
    reads = _reads()
    with ConsensusService(ServeConfig(workers=1)) as svc:
        h = svc.submit(_req(reads, _cfg(min_count=2)))
        h.result(timeout=300)
        h2 = svc.submit(_req(reads, _cfg(min_count=2)))
        h2.result(timeout=300)
        stats = svc.stats()
    assert "cache" not in stats
    assert h2.status is JobStatus.DONE


def _wait_deposits(svc, n, timeout_s=10.0):
    """Deposits land after ``result()`` returns: wait for them."""
    import time

    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if svc.stats().get("cache", {}).get("deposits", 0) >= n:
            return
        time.sleep(0.005)
    raise AssertionError(f"cache never saw {n} deposit(s)")
