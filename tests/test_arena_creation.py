"""On-device child creation engagement: structural regression tests.

Parity is covered by the fuzz/scenario suites; these assert the arena
actually ABSORBS vote splits (creation counters engage) and that the
blocking-dispatch count stays bounded — the round-5 performance
contract (evidence/DUAL_DISPATCH_r05.json: 168 -> 22 on the benchmark
shape; this test uses a smaller twin with a generous 2x headroom).
"""

import numpy as np

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.native import native_consensus, native_dual_consensus
from waffle_con_tpu.utils.example_gen import corrupt, generate_test

from waffle_con_tpu.ops.scorer import DISPATCH_COUNTER_KEYS as DISPATCH_KEYS


def _dual_workload(seq_len=200, per_hap=6, er=0.01):
    truth, reads1 = generate_test(4, seq_len, per_hap, er, seed=1)
    h2 = bytearray(truth)
    h2[seq_len // 3] = (h2[seq_len // 3] + 1) % 4
    h2[2 * seq_len // 3] = (h2[2 * seq_len // 3] + 2) % 4
    h2 = bytes(h2)
    reads2 = [
        corrupt(h2, er, np.random.default_rng(50 + i))
        for i in range(per_hap)
    ]
    return list(reads1) + reads2


def test_dual_split_creates_children_on_device():
    reads = _dual_workload()
    cfg = lambda b: (  # noqa: E731
        CdwfaConfigBuilder().backend(b).min_count(3).build()
    )
    want = native_dual_consensus(reads, config=cfg("native"))
    engine = DualConsensusDWFA(cfg("jax"))
    for r in reads:
        engine.add_sequence(r)
    got = engine.consensus()
    assert got == want
    c = engine.last_search_stats["scorer_counters"]
    # the split expansions must be absorbed in-kernel, not host-expanded
    assert c.get("arena_creations", 0) > 0
    assert c.get("arena_split_events", 0) > 0
    # dispatch budget: the r5 measurement for this shape is ~3 arena
    # calls + a handful of setup dispatches; 2x headroom for noise
    dispatches = sum(c.get(k, 0) for k in DISPATCH_KEYS)
    assert dispatches <= 30, c


def test_single_engine_tie_heavy_creates_children():
    # low min_count + noise makes multi-symbol single expansions common:
    # mode-1 creation (singles only) must absorb them in-kernel
    truth, reads = generate_test(4, 400, 8, 0.03, seed=3)
    cfg = lambda b: (  # noqa: E731
        CdwfaConfigBuilder().backend(b).min_count(2).build()
    )
    want = native_consensus(reads, config=cfg("native"))
    engine = ConsensusDWFA(cfg("jax"))
    for r in reads:
        engine.add_sequence(r)
    got = engine.consensus()
    assert [(x.sequence, x.scores) for x in got] == want
    c = engine.last_search_stats["scorer_counters"]
    assert c.get("arena_creations", 0) > 0
    assert c.get("arena_split_events", 0) > 0
