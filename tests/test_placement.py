"""Placement-policy contract tests: route admitted jobs by read count.

:class:`PlacementPolicy` classifies each admitted job — at or above
``large_read_threshold`` reads it becomes a mesh candidate, below it
stays on the ragged-arena path — and promotion rewrites the job's
config with an effective shard count clamped to the devices actually
available and pow2-floored.  The policy must never reject work: every
decline path returns ``None`` and the job runs unsharded.  The service
integration test pins byte-identical results for a mesh-promoted job
plus the ``mesh_placed`` counter.
"""

import dataclasses

import pytest

from waffle_con_tpu import CdwfaConfigBuilder
from waffle_con_tpu.serve import (
    ConsensusService,
    JobRequest,
    PlacementPolicy,
    ServeConfig,
)
from waffle_con_tpu.serve.service import _build_engine
from waffle_con_tpu.utils.example_gen import generate_test

pytestmark = pytest.mark.serve


def _jax_cfg(**kw):
    b = CdwfaConfigBuilder().backend("jax")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _request(n_reads, config, seq_len=100):
    _, reads = generate_test(4, seq_len, n_reads, 0.01, seed=n_reads)
    return JobRequest(kind="single", reads=tuple(reads), config=config)


# ----------------------------------------------------------- classifier


def test_classify_threshold_boundary():
    policy = PlacementPolicy(large_read_threshold=16, mesh_shards=2)
    cfg = _jax_cfg(min_count=2)
    assert policy.classify(_request(15, cfg)) == "arena"
    assert policy.classify(_request(16, cfg)) == "mesh"


def test_policy_validation():
    with pytest.raises(ValueError, match="large_read_threshold"):
        PlacementPolicy(large_read_threshold=0)
    with pytest.raises(ValueError, match="mesh_shards"):
        PlacementPolicy(mesh_shards=1)


def test_effective_shards_clamps_and_pow2_floors():
    policy = PlacementPolicy(large_read_threshold=16, mesh_shards=8)
    assert policy.effective_shards(100, 8) == 8
    # non-pow2 device pools round down so shards divide padded reads
    assert policy.effective_shards(100, 6) == 4
    assert policy.effective_shards(100, 3) == 2
    # the job's own read count caps the split too
    assert policy.effective_shards(3, 8) == 2
    # degenerate pools yield < 2: no promotion
    assert policy.effective_shards(100, 1) == 1
    assert policy.effective_shards(0, 8) == 0


# -------------------------------------------------------- place() paths


def test_place_declines_small_python_and_explicit():
    policy = PlacementPolicy(large_read_threshold=16, mesh_shards=2)
    jcfg = _jax_cfg(min_count=2)

    # small job: stays on the arena path
    assert policy.place(_request(8, jcfg), 8) is None
    # mesh_shards is a jax-scorer feature; python jobs never promote
    pcfg = CdwfaConfigBuilder().backend("python").min_count(2).build()
    assert policy.place(_request(24, pcfg), 8) is None
    # config-less jobs can't be rewritten
    assert policy.place(_request(24, None), 8) is None
    # explicit caller-pinned shard count wins over the policy
    pinned = dataclasses.replace(jcfg, mesh_shards=4)
    assert policy.place(_request(24, pinned), 8) is None
    # too few devices for >= 2 effective shards
    assert policy.place(_request(24, jcfg), 1) is None


def test_place_promotes_without_mutating_original():
    policy = PlacementPolicy(large_read_threshold=16, mesh_shards=4)
    cfg = _jax_cfg(min_count=2)
    request = _request(24, cfg)
    placed = policy.place(request, 8)
    assert placed is not None
    assert placed.config.mesh_shards == 4
    assert placed.reads == request.reads
    # promotion is a rewrite, not a mutation
    assert request.config.mesh_shards == 0
    assert cfg.mesh_shards == 0


def test_place_clamps_to_device_pool():
    policy = PlacementPolicy(large_read_threshold=16, mesh_shards=8)
    placed = policy.place(_request(24, _jax_cfg(min_count=2)), 2)
    assert placed is not None
    assert placed.config.mesh_shards == 2


# --------------------------------------------------- service integration


def test_served_mesh_job_byte_identical_to_serial():
    """A mesh-promoted job through the service equals the unsharded
    serial run of the same request, and the promotion is counted."""
    policy = PlacementPolicy(large_read_threshold=16, mesh_shards=2)
    cfg = _jax_cfg(min_count=2, initial_band=12)
    large = _request(16, cfg)
    small = _request(6, cfg, seq_len=80)
    want_large = _build_engine(large).consensus()
    want_small = _build_engine(small).consensus()

    with ConsensusService(
        ServeConfig(workers=2, batch_window_s=0.002, placement=policy)
    ) as svc:
        h_large = svc.submit(large)
        h_small = svc.submit(small)
        assert h_large.result(timeout=300) == want_large
        assert h_small.result(timeout=300) == want_small
        stats = svc.stats()

    assert stats["jobs"]["mesh_placed"] == 1
    assert stats["jobs"]["done"] == 2
