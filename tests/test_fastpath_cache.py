"""Fast-path dispatch cache: the engines must resolve the scorer's
optional-capability surface (run_extend / run_extend_dual / run_arena /
clone_push_many and the ARENA_* constants) a constant number of times
per search — NOT once per pop — and a supervised backend swap must
invalidate the cached snapshot via ``fastpath_gen``.
"""

import collections

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.ops.scorer import fast_paths, set_scorer_decorator
from waffle_con_tpu.runtime.supervisor import BackendSupervisor
from waffle_con_tpu.utils.example_gen import corrupt, generate_test

#: the optional-capability names the engines feature-test; resolving any
#: of these through the proxy stack is the cost the cache amortizes
FAST_PATH_NAMES = (
    "run_extend", "run_extend_dual", "run_arena", "clone_push_many",
    "ARENA_CAP", "ARENA_K", "ARENA_CRE_PER_EVENT", "ARENA_TAKE_MAX",
)


class _ProbeScorer:
    """Transparent delegating proxy that counts every dynamic resolution
    of a fast-path attribute (the same shape as CoalescingScorer /
    TimedScorer: plain ``__getattr__`` forwarding, two-way ``counters``)."""

    def __init__(self, base):
        self.__dict__["_base"] = base
        self.__dict__["probe_counts"] = collections.Counter()

    @property
    def counters(self):
        return self.__dict__["_base"].counters

    @counters.setter
    def counters(self, value):
        self.__dict__["_base"].counters = value

    def __getattr__(self, name):
        if name in FAST_PATH_NAMES:
            self.__dict__["probe_counts"][name] += 1
        return getattr(self.__dict__["_base"], name)


def _cfg(**kw):
    b = CdwfaConfigBuilder().min_count(2).backend("jax")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _run_probed(engine_cls, reads, cfg):
    probes = []

    def deco(scorer):
        p = _ProbeScorer(scorer)
        probes.append(p)
        return p

    prev = set_scorer_decorator(deco)
    try:
        e = engine_cls(cfg)
        for r in reads:
            e.add_sequence(r)
        result = e.consensus()
    finally:
        set_scorer_decorator(prev)
    counts = collections.Counter()
    for p in probes:
        counts.update(p.probe_counts)
    return result, counts


def _single_reads(seq_len, n=6, seed=0):
    _, reads = generate_test(4, seq_len, n, 0.01, seed=seed)
    return list(reads)


def _dual_reads(seq_len, half=4, seed=0):
    truth, reads1 = generate_test(4, seq_len, half, 0.01, seed=seed)
    h2 = bytearray(truth)
    rng = np.random.default_rng(seed + 7)
    for pos in rng.choice(seq_len, size=2, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    return list(reads1) + [
        corrupt(bytes(h2), 0.01, np.random.default_rng(seed + 50 + i))
        for i in range(half)
    ]


@pytest.mark.parametrize(
    "engine_cls,maker",
    [(ConsensusDWFA, _single_reads), (DualConsensusDWFA, _dual_reads)],
    ids=["single", "dual"],
)
def test_per_pop_dispatch_does_constant_proxy_probes(engine_cls, maker):
    """O(1) regression: growing the workload ~6x (hence the pop count)
    must NOT grow the number of fast-path resolutions through the proxy
    stack — the per-search probe count is a small constant."""
    small_res, small_counts = _run_probed(engine_cls, maker(60), _cfg())
    large_res, large_counts = _run_probed(engine_cls, maker(380), _cfg())
    assert small_res and large_res  # both searches actually completed
    assert large_counts == small_counts
    assert large_counts, "probe saw no fast-path resolutions at all"
    assert max(large_counts.values()) <= 4, dict(large_counts)


def test_probe_decorator_is_transparent():
    """The counting proxy itself must not perturb results: probed and
    unprobed runs of the same workload are byte-identical."""
    reads = _single_reads(150, seed=3)
    probed, _ = _run_probed(ConsensusDWFA, reads, _cfg())
    e = ConsensusDWFA(_cfg())
    for r in reads:
        e.add_sequence(r)
    plain = e.consensus()
    assert [(c.sequence, c.scores) for c in probed] == [
        (c.sequence, c.scores) for c in plain
    ]


def test_fastpath_cache_hit_and_gen_invalidation():
    """fast_paths() returns the SAME snapshot while ``fastpath_gen`` is
    stable and a fresh one after a supervised demotion bumps it."""
    cfg = _cfg(backend_chain=("python", "jax"))
    reads = [bytes([0, 1, 2, 3] * 4)] * 3
    sup = BackendSupervisor(reads, cfg)
    fp1 = fast_paths(sup)
    assert fast_paths(sup) is fp1  # cache hit while gen is stable
    gen0 = sup.fastpath_gen
    sup._demote(RuntimeError("injected demotion"))
    assert sup.fastpath_gen == gen0 + 1
    fp2 = fast_paths(sup)
    assert fp2 is not fp1
    assert fp2.gen == sup.fastpath_gen
    assert fast_paths(sup) is fp2
