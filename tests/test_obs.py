"""Observability subsystem: tracer, metrics registry, search reports.

Covers the obs contracts end to end: span nesting and Chrome export,
the disabled-mode zero-allocation guarantee, histogram bucket math,
Prometheus text exposition, the event-log saturation counter, dispatch
instrumentation through ``construct_backend``, and supervisor demotion
events landing in the metrics registry.
"""

import json
import logging

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.obs.instrument import TimedScorer, maybe_instrument
from waffle_con_tpu.obs.metrics import Histogram, MetricsRegistry
from waffle_con_tpu.obs.report import SearchReport
from waffle_con_tpu.obs.trace import NULL_SPAN, Tracer
from waffle_con_tpu.ops.scorer import construct_backend
from waffle_con_tpu.runtime import events

SINGLE_READS = (b"ACGTACGT", b"ACGTACGT", b"ACCTACGT")


def _cfg(**kw):
    b = CdwfaConfigBuilder().min_count(1).backend("jax")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


@pytest.fixture
def obs_on():
    """Metrics + tracing force-enabled on a clean registry/tracer;
    teardown restores the env-driven defaults so no obs state leaks."""
    obs_metrics.enable_metrics(True)
    obs_metrics.registry().reset()
    tracer = obs_trace.get_tracer()
    tracer.enable(True)
    tracer.clear()
    try:
        yield tracer
    finally:
        obs_metrics.reset_metrics_enabled()
        obs_metrics.registry().reset()
        tracer.reset_enabled()
        tracer.clear()


# ------------------------------------------------------------------ tracer


def test_tracer_nested_spans_contained():
    t = Tracer()
    t.enable(True)
    with t.span("outer", "search", engine="single"):
        with t.span("inner", "dispatch", backend="jax"):
            pass
    evs = t.chrome_events()
    assert [e["name"] for e in evs] == ["inner", "outer"]  # exit order
    inner, outer = evs
    # Chrome complete-event shape
    for e in evs:
        assert e["ph"] == "X"
        assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
    # the child's [ts, ts+dur] interval nests inside the parent's
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert inner["args"] == {"backend": "jax"}
    totals = t.category_totals()
    assert set(totals) == {"search", "dispatch"}
    assert totals["search"] >= totals["dispatch"]


def test_tracer_disabled_is_allocation_free():
    t = Tracer()  # WAFFLE_TRACE unset in tier-1 runs -> disabled
    t.enable(False)
    s1 = t.span("a", "host")
    s2 = t.span("b", "dispatch", key="value")
    # the no-op singleton is shared: no per-span allocation at all
    assert s1 is NULL_SPAN and s2 is NULL_SPAN
    with s1:
        pass
    assert t.chrome_events() == []
    assert t.category_totals() == {}


def test_tracer_chrome_trace_file(tmp_path):
    t = Tracer()
    t.enable(True)
    with t.span("search", "search"):
        pass
    path = tmp_path / "trace.json"
    t.write_chrome_trace(str(path))
    payload = json.loads(path.read_text())
    assert payload["displayTimeUnit"] == "ms"
    assert payload["traceEvents"][0]["name"] == "search"


def test_tracer_clear_resets_events_and_totals():
    t = Tracer()
    t.enable(True)
    with t.span("x", "host"):
        pass
    assert t.chrome_events()
    t.clear()
    assert t.chrome_events() == [] and t.category_totals() == {}


# ------------------------------------------------------------- histograms


def test_histogram_bucket_math():
    h = Histogram(bounds=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.001, 0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    # bounds are inclusive upper edges; the last slot is +Inf overflow
    assert h.counts == [2, 1, 1, 2]
    assert h.cumulative() == [2, 3, 4, 6]
    assert h.count == 6
    assert h.sum == pytest.approx(5.5565)


def test_histogram_rejects_empty_bounds():
    with pytest.raises(ValueError):
        Histogram(bounds=())


def test_registry_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("c_total", kind="x").inc(3)
    reg.gauge("g_depth").set(7)
    reg.histogram("h_lat", buckets=(1.0, 2.0), backend="jax").observe(1.5)
    snap = reg.snapshot()
    assert snap["c_total"]["type"] == "counter"
    assert snap["c_total"]["series"]['{kind="x"}'] == 3
    assert snap["g_depth"]["series"]["{}"] == 7
    hist = snap["h_lat"]["series"]['{backend="jax"}']
    assert hist["buckets"] == {"1.0": 0, "2.0": 1}
    assert hist["overflow"] == 0
    assert hist["count"] == 1 and hist["sum"] == pytest.approx(1.5)


def test_registry_type_stability():
    reg = MetricsRegistry()
    reg.counter("m")
    with pytest.raises(ValueError):
        reg.gauge("m")


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("waffle_x_total", backend="jax").inc(2)
    reg.gauge("waffle_depth").set(4)
    h = reg.histogram("waffle_lat_seconds", buckets=(0.1, 1.0), op="push")
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render_prometheus()
    lines = text.strip().splitlines()
    assert "# TYPE waffle_x_total counter" in lines
    assert 'waffle_x_total{backend="jax"} 2.0' in lines
    assert "waffle_depth 4.0" in lines
    assert "# TYPE waffle_lat_seconds histogram" in lines
    # cumulative le buckets with the +Inf total last
    assert 'waffle_lat_seconds_bucket{op="push",le="0.1"} 1' in lines
    assert 'waffle_lat_seconds_bucket{op="push",le="1.0"} 2' in lines
    assert 'waffle_lat_seconds_bucket{op="push",le="+Inf"} 3' in lines
    assert 'waffle_lat_seconds_count{op="push"} 3' in lines
    assert any(
        line.startswith('waffle_lat_seconds_sum{op="push"}') for line in lines
    )


# ------------------------------------------------------------- event log


def test_event_log_saturation_counts_drops(monkeypatch):
    events.clear_events()
    monkeypatch.setattr(events, "_MAX_EVENTS", 3)
    try:
        for i in range(6):
            events.record("test_event", i=i)
        evs = events.get_events()
        # cap=3: three stored events, then the marker rides along as the
        # one out-of-cap entry counting every further drop
        assert len(evs) == 4
        assert evs[-1]["kind"] == "event_log_saturated"
        assert evs[-1]["dropped"] == 3
        summary = events.summarize_events()
        assert summary == {"test_event": 3, "event_log_saturated": 1}
    finally:
        events.clear_events()


def test_event_log_feeds_metrics_registry(obs_on):
    events.clear_events()
    try:
        events.record("unit_test_kind")
        events.record("unit_test_kind")
        snap = obs_metrics.registry().snapshot()
        series = snap["waffle_runtime_events_total"]["series"]
        assert series['{kind="unit_test_kind"}'] == 2
    finally:
        events.clear_events()


# ------------------------------------------------- dispatch instrumentation


def test_construct_backend_plain_when_disabled():
    from waffle_con_tpu.ops.scorer import PythonScorer

    scorer = construct_backend(list(SINGLE_READS), _cfg(), "python")
    assert isinstance(scorer, PythonScorer)


def test_timed_scorer_records_latency_histograms(obs_on):
    scorer = construct_backend(list(SINGLE_READS), _cfg(), "python")
    assert isinstance(scorer, TimedScorer)
    # feature-test transparency: the python oracle has no run kernels
    assert getattr(scorer, "run_extend", None) is None
    h = scorer.root(np.ones(len(SINGLE_READS), dtype=bool))
    scorer.push(h, b"A")
    scorer.stats(h, b"A")
    snap = obs_metrics.registry().snapshot()
    latency = snap["waffle_dispatch_latency_seconds"]["series"]
    key_push = '{backend="python",op="push"}'
    assert latency[key_push]["count"] == 1
    assert latency['{backend="python",op="stats"}']["count"] == 1
    totals = snap["waffle_dispatch_total"]["series"]
    assert totals[key_push] == 1


def test_timed_scorer_counters_stay_live(obs_on):
    from waffle_con_tpu.ops.scorer import PythonScorer

    scorer = maybe_instrument(
        PythonScorer(list(SINGLE_READS), _cfg()), "python"
    )
    assert isinstance(scorer, TimedScorer)
    # the supervisor adopts counters by plain assignment; the proxy must
    # forward BOTH directions to the wrapped backend
    shared = {"adopted": 1}
    scorer.counters = shared
    assert scorer._base.counters is shared
    h = scorer.root(np.ones(len(SINGLE_READS), dtype=bool))
    scorer.push(h, b"A")
    assert shared["push_calls"] == 1  # backend increments land in shared


def test_supervisor_demotion_lands_in_metrics(obs_on, faults):
    faults.add("timeout", backend="jax", at=3, count=None)
    faults.add("timeout", backend="jax", at=4, count=None)
    cfg = _cfg(
        backend_chain=("python",),
        dispatch_retries=1,
        breaker_threshold=2,
        retry_backoff_s=0.0,
    )
    engine = ConsensusDWFA(cfg)
    for r in SINGLE_READS:
        engine.add_sequence(r)
    results = engine.consensus()
    assert results[0].sequence == b"ACGTACGT"
    assert events.get_events("backend_demoted")  # the fault really fired
    snap = obs_metrics.registry().snapshot()
    demotions = snap["waffle_backend_demotions_total"]["series"]
    key = '{from_backend="jax",to_backend="python"}'
    assert demotions[key] == 1
    failures = snap["waffle_dispatch_failures_total"]["series"]
    assert sum(failures.values()) >= 2
    # the demoted search's report names the backend that finished it
    assert engine.last_search_report.backend == "python"


# --------------------------------------------------------- search reports


def test_search_report_from_single_engine(obs_on):
    engine = ConsensusDWFA(_cfg(backend="python"))
    for r in SINGLE_READS:
        engine.add_sequence(r)
    results = engine.consensus()
    rep = engine.last_search_report
    assert isinstance(rep, SearchReport)
    assert rep.engine == "single" and rep.backend == "python"
    assert rep.nodes_explored > 0 and rep.dispatch_total > 0
    assert rep.n_results == len(results)
    assert rep.consensus_len == len(results[0].sequence)
    assert rep.wall_s > 0
    d = rep.to_dict()
    assert d["engine"] == "single"
    assert "dispatch" in d["time_breakdown"]  # spans were recording
    assert rep.summary_line().startswith("search summary: engine=single")
    # engine searches also bump the registry-side search metrics
    snap = obs_metrics.registry().snapshot()
    assert snap["waffle_searches_total"]["series"]['{engine="single"}'] == 1


def test_search_report_dual_peak_queue(obs_on):
    engine = DualConsensusDWFA(_cfg(backend="python"))
    for r in (b"ACGTACGT", b"ACGTACGT", b"ACTTACGT", b"ACTTACGT"):
        engine.add_sequence(r)
    engine.consensus()
    rep = engine.last_search_report
    assert rep.engine == "dual"
    assert rep.peak_queue_size > 0  # the satellite: dual now tracks it
    assert engine.last_search_stats["peak_queue_size"] == rep.peak_queue_size


def test_search_report_without_obs_enabled():
    # reports are built unconditionally (cheap); only spans/metrics gate
    engine = ConsensusDWFA(_cfg(backend="python"))
    for r in SINGLE_READS:
        engine.add_sequence(r)
    engine.consensus()
    rep = engine.last_search_report
    assert rep.nodes_explored > 0
    assert rep.time_breakdown == {}  # no tracer -> no breakdown


# ----------------------------------------------- bucket-index semantics


def _naive_bucket_index(bounds, value):
    """The linear scan `_bucket_index` replaced — the semantic oracle."""
    for i, b in enumerate(bounds):
        if value <= b:
            return i
    return len(bounds)


def test_bucket_index_matches_naive_scan_property():
    """Property-style sweep: the bisect-based index agrees with the
    naive scan everywhere, including values exactly ON an upper bound
    (inclusive), just below/above it, and the NaN/inf edges."""
    rng = np.random.default_rng(7)
    bound_sets = [
        (0.001,),
        (0.001, 0.01, 0.1),
        obs_metrics.DEFAULT_LATENCY_BUCKETS,
        obs_metrics.DEFAULT_COUNT_BUCKETS,
        tuple(sorted(rng.uniform(-10, 10, size=13))),
    ]
    for bounds in bound_sets:
        h = Histogram(bounds=bounds)
        probes = list(h.bounds)                            # exactly on
        probes += [b - 1e-12 for b in h.bounds]            # just below
        probes += [b + 1e-12 for b in h.bounds]            # just above
        probes += list(rng.uniform(-20, 20, size=200))
        probes += [0.0, -1e30, 1e30, float("inf"), float("-inf"),
                   float("nan")]
        for v in probes:
            assert h._bucket_index(v) == _naive_bucket_index(h.bounds, v), (
                bounds, v,
            )


def test_bucket_index_exact_upper_bound_is_inclusive():
    h = Histogram(bounds=(1.0, 2.0, 4.0))
    h.observe(2.0)  # exactly on a bound -> that bucket, not the next
    assert h.counts == [0, 1, 0, 0]


# --------------------------------------- event-log drop counter satellite


def test_event_log_drops_surface_as_registry_counter(obs_on, monkeypatch):
    monkeypatch.setattr(events, "_MAX_EVENTS", 4)
    events.clear_events()
    try:
        for i in range(9):
            events.record("drop_probe", i=i)
        log = events.get_events()
        assert log[-1]["kind"] == "event_log_saturated"
        dropped_in_marker = log[-1]["dropped"]
        snap = obs_metrics.registry().snapshot()
        counter = snap["waffle_runtime_events_dropped_total"]["series"]["{}"]
        assert counter == dropped_in_marker == 5
    finally:
        events.clear_events()


# ----------------------------------------------------- rolling SLO windows


@pytest.fixture
def slo_clean():
    from waffle_con_tpu.obs import flight, slo

    flight.reset()
    slo.reset()
    try:
        yield slo
    finally:
        flight.reset()
        slo.reset()


def test_rolling_window_percentiles_and_ewma(slo_clean):
    from waffle_con_tpu.obs.slo import RollingWindow

    w = RollingWindow(max_age_s=300.0, max_count=1000)
    for v in range(1, 101):  # 1..100 ms
        w.observe(v / 1000.0)
    p = w.percentiles()
    assert p["p50"] == pytest.approx(0.050)
    assert p["p95"] == pytest.approx(0.095)
    assert p["p99"] == pytest.approx(0.099)
    assert 0.0 < w.ewma < 0.1
    assert len(w) == 100


def test_rolling_window_expires_old_samples(slo_clean):
    from waffle_con_tpu.obs.slo import RollingWindow

    w = RollingWindow(max_age_s=10.0, max_count=1000)
    w.observe(5.0, now=100.0)      # will age out
    w.observe(0.001, now=109.0)
    assert w.percentiles(now=111.0)["p99"] == pytest.approx(0.001)
    assert len(w) == 1


def test_slow_search_checked_against_prior_baseline(slo_clean):
    slo = slo_clean
    for _ in range(30):
        assert slo.observe_search(0.01) is False
    # 1s >> 3 x p95(10ms): flagged, and judged BEFORE joining the window
    assert slo.observe_search(1.0) is True
    # the outlier joined the window afterwards; an identical repeat is
    # now judged against the diluted window but p95 is still ~10ms
    snap = slo.snapshot()
    assert snap["slow_searches"] == 1
    assert snap["job"]["count"] == 31


def test_slo_collector_publishes_into_exposition(obs_on, slo_clean):
    slo = slo_clean
    for v in (0.01, 0.02, 0.03):
        slo.observe_dispatch(v)
    slo.observe_job(0.5)
    text = obs_metrics.registry().render_prometheus()
    assert "waffle_slo_dispatch_latency_seconds" in text
    assert "waffle_slo_job_latency_seconds" in text
    assert 'quantile="p95"' in text and 'quantile="ewma"' in text
    snap = obs_metrics.registry().snapshot()
    assert "waffle_slo_window_samples" in snap


def test_cold_tracker_leaves_registry_untouched(slo_clean):
    reg = MetricsRegistry()
    from waffle_con_tpu.obs.slo import SloTracker

    SloTracker().publish(reg)
    assert reg.snapshot() == {}


# ------------------------------------------------------- flight recorder


def test_flight_ring_is_bounded_and_filterable(slo_clean):
    from waffle_con_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(ring_size=16)
    for i in range(40):
        rec.record("probe", trace_id=f"t{i % 2}", i=i)
    records = rec.records()
    assert len(records) == 16  # bounded
    assert records[-1]["i"] == 39
    only_t0 = rec.records(trace_id="t0")
    assert only_t0 and all(r["trace_id"] == "t0" for r in only_t0)


def test_flight_trigger_dedupes_and_stays_in_memory(slo_clean, tmp_path,
                                                    monkeypatch):
    from waffle_con_tpu.obs import flight

    monkeypatch.delenv("WAFFLE_FLIGHT_DIR", raising=False)
    flight.record("step", trace_id="job-1", n=1)
    first = flight.trigger("deadline_exceeded", trace_id="job-1",
                           overrun_s=0.2)
    assert first is not None
    assert first["trace"] and first["trace"][0]["kind"] == "step"
    assert "path" not in first  # no dir -> memory only, no file
    # same (reason, trace) dedupes; a different trace id still fires
    assert flight.trigger("deadline_exceeded", trace_id="job-1") is None
    assert flight.trigger("deadline_exceeded", trace_id="job-2") is not None
    assert len(flight.incidents()) == 2


def test_flight_dedupe_window_expires(slo_clean, monkeypatch):
    """Dedupe is a rolling window, not forever: the same (reason,
    trace) re-fires once the window has passed, and window 0 disables
    dedupe entirely."""
    from waffle_con_tpu.obs import flight
    from waffle_con_tpu.obs.flight import FlightRecorder

    monkeypatch.delenv("WAFFLE_FLIGHT_DIR", raising=False)
    rec = FlightRecorder(dedupe_s=10.0)
    t = [1000.0]
    monkeypatch.setattr(flight.time, "time", lambda: t[0])
    assert rec.trigger("slow_search", trace_id="job-1") is not None
    t[0] += 5.0  # inside the window: suppressed
    assert rec.trigger("slow_search", trace_id="job-1") is None
    t[0] += 6.0  # 11s after the first fire: window expired, re-fires
    assert rec.trigger("slow_search", trace_id="job-1") is not None
    assert len(rec.incidents()) == 2

    zero = FlightRecorder(dedupe_s=0.0)
    assert zero.trigger("slow_search", trace_id="j") is not None
    assert zero.trigger("slow_search", trace_id="j") is not None


def test_flight_dedupe_window_env_knob(slo_clean, monkeypatch):
    from waffle_con_tpu.obs.flight import (
        DEFAULT_DEDUPE_S,
        _dedupe_window_s,
    )

    monkeypatch.delenv("WAFFLE_FLIGHT_DEDUPE_S", raising=False)
    assert _dedupe_window_s() == DEFAULT_DEDUPE_S == 300.0
    monkeypatch.setenv("WAFFLE_FLIGHT_DEDUPE_S", "7.5")
    assert _dedupe_window_s() == 7.5
    monkeypatch.setenv("WAFFLE_FLIGHT_DEDUPE_S", "bogus")
    assert _dedupe_window_s() == DEFAULT_DEDUPE_S


def test_flight_dump_writes_parseable_incident(slo_clean, tmp_path,
                                               monkeypatch):
    from waffle_con_tpu.obs import flight

    monkeypatch.setenv("WAFFLE_FLIGHT_DIR", str(tmp_path))
    flight.record("step", trace_id="job-9", n=1)
    incident = flight.trigger("watchdog_budget_exceeded",
                              trace_id="job-9", total=10, budget=5)
    files = list(tmp_path.glob("incident-*-watchdog_budget_exceeded.json"))
    assert len(files) == 1
    on_disk = json.loads(files[0].read_text())
    assert on_disk["schema"] == "waffle-flight-incident/1"
    assert on_disk["reason"] == "watchdog_budget_exceeded"
    assert on_disk["detail"] == {"total": 10, "budget": 5}
    assert on_disk["trace_id"] == "job-9"
    assert incident["path"] == str(files[0])
