"""Observability subsystem: tracer, metrics registry, search reports.

Covers the obs contracts end to end: span nesting and Chrome export,
the disabled-mode zero-allocation guarantee, histogram bucket math,
Prometheus text exposition, the event-log saturation counter, dispatch
instrumentation through ``construct_backend``, and supervisor demotion
events landing in the metrics registry.
"""

import json
import logging

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.obs.instrument import TimedScorer, maybe_instrument
from waffle_con_tpu.obs.metrics import Histogram, MetricsRegistry
from waffle_con_tpu.obs.report import SearchReport
from waffle_con_tpu.obs.trace import NULL_SPAN, Tracer
from waffle_con_tpu.ops.scorer import construct_backend
from waffle_con_tpu.runtime import events

SINGLE_READS = (b"ACGTACGT", b"ACGTACGT", b"ACCTACGT")


def _cfg(**kw):
    b = CdwfaConfigBuilder().min_count(1).backend("jax")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


@pytest.fixture
def obs_on():
    """Metrics + tracing force-enabled on a clean registry/tracer;
    teardown restores the env-driven defaults so no obs state leaks."""
    obs_metrics.enable_metrics(True)
    obs_metrics.registry().reset()
    tracer = obs_trace.get_tracer()
    tracer.enable(True)
    tracer.clear()
    try:
        yield tracer
    finally:
        obs_metrics.reset_metrics_enabled()
        obs_metrics.registry().reset()
        tracer.reset_enabled()
        tracer.clear()


# ------------------------------------------------------------------ tracer


def test_tracer_nested_spans_contained():
    t = Tracer()
    t.enable(True)
    with t.span("outer", "search", engine="single"):
        with t.span("inner", "dispatch", backend="jax"):
            pass
    evs = t.chrome_events()
    assert [e["name"] for e in evs] == ["inner", "outer"]  # exit order
    inner, outer = evs
    # Chrome complete-event shape
    for e in evs:
        assert e["ph"] == "X"
        assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
    # the child's [ts, ts+dur] interval nests inside the parent's
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert inner["args"] == {"backend": "jax"}
    totals = t.category_totals()
    assert set(totals) == {"search", "dispatch"}
    assert totals["search"] >= totals["dispatch"]


def test_tracer_disabled_is_allocation_free():
    t = Tracer()  # WAFFLE_TRACE unset in tier-1 runs -> disabled
    t.enable(False)
    s1 = t.span("a", "host")
    s2 = t.span("b", "dispatch", key="value")
    # the no-op singleton is shared: no per-span allocation at all
    assert s1 is NULL_SPAN and s2 is NULL_SPAN
    with s1:
        pass
    assert t.chrome_events() == []
    assert t.category_totals() == {}


def test_tracer_chrome_trace_file(tmp_path):
    t = Tracer()
    t.enable(True)
    with t.span("search", "search"):
        pass
    path = tmp_path / "trace.json"
    t.write_chrome_trace(str(path))
    payload = json.loads(path.read_text())
    assert payload["displayTimeUnit"] == "ms"
    assert payload["traceEvents"][0]["name"] == "search"


def test_tracer_clear_resets_events_and_totals():
    t = Tracer()
    t.enable(True)
    with t.span("x", "host"):
        pass
    assert t.chrome_events()
    t.clear()
    assert t.chrome_events() == [] and t.category_totals() == {}


# ------------------------------------------------------------- histograms


def test_histogram_bucket_math():
    h = Histogram(bounds=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.001, 0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    # bounds are inclusive upper edges; the last slot is +Inf overflow
    assert h.counts == [2, 1, 1, 2]
    assert h.cumulative() == [2, 3, 4, 6]
    assert h.count == 6
    assert h.sum == pytest.approx(5.5565)


def test_histogram_rejects_empty_bounds():
    with pytest.raises(ValueError):
        Histogram(bounds=())


def test_registry_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("c_total", kind="x").inc(3)
    reg.gauge("g_depth").set(7)
    reg.histogram("h_lat", buckets=(1.0, 2.0), backend="jax").observe(1.5)
    snap = reg.snapshot()
    assert snap["c_total"]["type"] == "counter"
    assert snap["c_total"]["series"]['{kind="x"}'] == 3
    assert snap["g_depth"]["series"]["{}"] == 7
    hist = snap["h_lat"]["series"]['{backend="jax"}']
    assert hist["buckets"] == {"1.0": 0, "2.0": 1}
    assert hist["overflow"] == 0
    assert hist["count"] == 1 and hist["sum"] == pytest.approx(1.5)


def test_registry_type_stability():
    reg = MetricsRegistry()
    reg.counter("m")
    with pytest.raises(ValueError):
        reg.gauge("m")


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("waffle_x_total", backend="jax").inc(2)
    reg.gauge("waffle_depth").set(4)
    h = reg.histogram("waffle_lat_seconds", buckets=(0.1, 1.0), op="push")
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render_prometheus()
    lines = text.strip().splitlines()
    assert "# TYPE waffle_x_total counter" in lines
    assert 'waffle_x_total{backend="jax"} 2.0' in lines
    assert "waffle_depth 4.0" in lines
    assert "# TYPE waffle_lat_seconds histogram" in lines
    # cumulative le buckets with the +Inf total last
    assert 'waffle_lat_seconds_bucket{op="push",le="0.1"} 1' in lines
    assert 'waffle_lat_seconds_bucket{op="push",le="1.0"} 2' in lines
    assert 'waffle_lat_seconds_bucket{op="push",le="+Inf"} 3' in lines
    assert 'waffle_lat_seconds_count{op="push"} 3' in lines
    assert any(
        line.startswith('waffle_lat_seconds_sum{op="push"}') for line in lines
    )


# ------------------------------------------------------------- event log


def test_event_log_saturation_counts_drops(monkeypatch):
    events.clear_events()
    monkeypatch.setattr(events, "_MAX_EVENTS", 3)
    try:
        for i in range(6):
            events.record("test_event", i=i)
        evs = events.get_events()
        # cap=3: three stored events, then the marker rides along as the
        # one out-of-cap entry counting every further drop
        assert len(evs) == 4
        assert evs[-1]["kind"] == "event_log_saturated"
        assert evs[-1]["dropped"] == 3
        summary = events.summarize_events()
        assert summary == {"test_event": 3, "event_log_saturated": 1}
    finally:
        events.clear_events()


def test_event_log_feeds_metrics_registry(obs_on):
    events.clear_events()
    try:
        events.record("unit_test_kind")
        events.record("unit_test_kind")
        snap = obs_metrics.registry().snapshot()
        series = snap["waffle_runtime_events_total"]["series"]
        assert series['{kind="unit_test_kind"}'] == 2
    finally:
        events.clear_events()


# ------------------------------------------------- dispatch instrumentation


def test_construct_backend_plain_when_disabled():
    from waffle_con_tpu.ops.scorer import PythonScorer

    scorer = construct_backend(list(SINGLE_READS), _cfg(), "python")
    assert isinstance(scorer, PythonScorer)


def test_timed_scorer_records_latency_histograms(obs_on):
    scorer = construct_backend(list(SINGLE_READS), _cfg(), "python")
    assert isinstance(scorer, TimedScorer)
    # feature-test transparency: the python oracle has no run kernels
    assert getattr(scorer, "run_extend", None) is None
    h = scorer.root(np.ones(len(SINGLE_READS), dtype=bool))
    scorer.push(h, b"A")
    scorer.stats(h, b"A")
    snap = obs_metrics.registry().snapshot()
    latency = snap["waffle_dispatch_latency_seconds"]["series"]
    key_push = '{backend="python",op="push"}'
    assert latency[key_push]["count"] == 1
    assert latency['{backend="python",op="stats"}']["count"] == 1
    totals = snap["waffle_dispatch_total"]["series"]
    assert totals[key_push] == 1


def test_timed_scorer_counters_stay_live(obs_on):
    from waffle_con_tpu.ops.scorer import PythonScorer

    scorer = maybe_instrument(
        PythonScorer(list(SINGLE_READS), _cfg()), "python"
    )
    assert isinstance(scorer, TimedScorer)
    # the supervisor adopts counters by plain assignment; the proxy must
    # forward BOTH directions to the wrapped backend
    shared = {"adopted": 1}
    scorer.counters = shared
    assert scorer._base.counters is shared
    h = scorer.root(np.ones(len(SINGLE_READS), dtype=bool))
    scorer.push(h, b"A")
    assert shared["push_calls"] == 1  # backend increments land in shared


def test_supervisor_demotion_lands_in_metrics(obs_on, faults):
    faults.add("timeout", backend="jax", at=3, count=None)
    faults.add("timeout", backend="jax", at=4, count=None)
    cfg = _cfg(
        backend_chain=("python",),
        dispatch_retries=1,
        breaker_threshold=2,
        retry_backoff_s=0.0,
    )
    engine = ConsensusDWFA(cfg)
    for r in SINGLE_READS:
        engine.add_sequence(r)
    results = engine.consensus()
    assert results[0].sequence == b"ACGTACGT"
    assert events.get_events("backend_demoted")  # the fault really fired
    snap = obs_metrics.registry().snapshot()
    demotions = snap["waffle_backend_demotions_total"]["series"]
    key = '{from_backend="jax",to_backend="python"}'
    assert demotions[key] == 1
    failures = snap["waffle_dispatch_failures_total"]["series"]
    assert sum(failures.values()) >= 2
    # the demoted search's report names the backend that finished it
    assert engine.last_search_report.backend == "python"


# --------------------------------------------------------- search reports


def test_search_report_from_single_engine(obs_on):
    engine = ConsensusDWFA(_cfg(backend="python"))
    for r in SINGLE_READS:
        engine.add_sequence(r)
    results = engine.consensus()
    rep = engine.last_search_report
    assert isinstance(rep, SearchReport)
    assert rep.engine == "single" and rep.backend == "python"
    assert rep.nodes_explored > 0 and rep.dispatch_total > 0
    assert rep.n_results == len(results)
    assert rep.consensus_len == len(results[0].sequence)
    assert rep.wall_s > 0
    d = rep.to_dict()
    assert d["engine"] == "single"
    assert "dispatch" in d["time_breakdown"]  # spans were recording
    assert rep.summary_line().startswith("search summary: engine=single")
    # engine searches also bump the registry-side search metrics
    snap = obs_metrics.registry().snapshot()
    assert snap["waffle_searches_total"]["series"]['{engine="single"}'] == 1


def test_search_report_dual_peak_queue(obs_on):
    engine = DualConsensusDWFA(_cfg(backend="python"))
    for r in (b"ACGTACGT", b"ACGTACGT", b"ACTTACGT", b"ACTTACGT"):
        engine.add_sequence(r)
    engine.consensus()
    rep = engine.last_search_report
    assert rep.engine == "dual"
    assert rep.peak_queue_size > 0  # the satellite: dual now tracks it
    assert engine.last_search_stats["peak_queue_size"] == rep.peak_queue_size


def test_search_report_without_obs_enabled():
    # reports are built unconditionally (cheap); only spans/metrics gate
    engine = ConsensusDWFA(_cfg(backend="python"))
    for r in SINGLE_READS:
        engine.add_sequence(r)
    engine.consensus()
    rep = engine.last_search_report
    assert rep.nodes_explored > 0
    assert rep.time_breakdown == {}  # no tracer -> no breakdown
