"""min_af != 0 on the device fast paths.

Round-4 verdict weak #3: ``min_af != 0`` used to disable every device
fast path (the run/arena vote thresholds were static scalars).  The
kernels now take the host's exact dynamic-min-count tables
(``mc_tab``/``imb_tab`` — /root/reference/src/dual_consensus.rs:326-336,
497-513), so a dual search with ``min_af`` set must still engage the
run/arena kernels and stay byte-identical to the native oracle.
"""

import numpy as np
import pytest

from waffle_con_tpu import CdwfaConfigBuilder, DualConsensusDWFA
from waffle_con_tpu.native import native_dual_consensus
from waffle_con_tpu.utils.example_gen import generate_test, corrupt


def _dual_reads(seq_len, per_hap, error_rate=0.01):
    truth, reads1 = generate_test(4, seq_len, per_hap, error_rate, seed=11)
    h2 = bytearray(truth)
    h2[seq_len // 3] = (h2[seq_len // 3] + 1) % 4
    h2[2 * seq_len // 3] = (h2[2 * seq_len // 3] + 2) % 4
    h2 = bytes(h2)
    reads2 = [
        corrupt(h2, error_rate, np.random.default_rng(700 + i))
        for i in range(per_hap)
    ]
    return list(reads1) + reads2


def _cfg(backend, min_af, **kw):
    b = (
        CdwfaConfigBuilder()
        .min_count(2)
        .min_af(min_af)
        .backend(backend)
    )
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


@pytest.mark.parametrize("min_af", [0.2, 0.25, 0.4])
def test_min_af_dual_parity_and_engagement(min_af):
    reads = _dual_reads(400, 6)
    oracle = native_dual_consensus(reads, config=_cfg("native", min_af))
    engine = DualConsensusDWFA(_cfg("jax", min_af))
    for r in reads:
        engine.add_sequence(r)
    got = engine.consensus()
    assert got == oracle
    c = engine.last_search_stats["scorer_counters"]
    # the whole point: the device fast paths must engage despite min_af
    assert (
        c.get("run_dual_steps", 0)
        + c.get("arena_steps", 0)
        + c.get("run_steps", 0)
    ) > 0


def test_min_af_with_offsets_dynamic_table_parity():
    # late-activating reads make active_min_count genuinely non-constant:
    # the uploaded imb table must match the host's lazy extension exactly
    reads = _dual_reads(300, 5)
    offsets = [None] * len(reads)
    late1 = corrupt(reads[0][100:], 0.01, np.random.default_rng(901))
    late2 = corrupt(reads[5][120:], 0.01, np.random.default_rng(902))
    reads += [late1, late2]
    offsets += [100, 120]

    def run(backend):
        if backend == "native":
            return native_dual_consensus(
                reads, offsets=offsets, config=_cfg("native", 0.25)
            )
        engine = DualConsensusDWFA(_cfg("jax", 0.25))
        for r, off in zip(reads, offsets):
            engine.add_sequence_offset(r, off)
        return engine.consensus()

    assert run("jax") == run("native")


def test_min_af_weighted_falls_back_with_parity():
    # weighted_by_ed + min_af: vote totals are fractional, so the device
    # tables don't apply — the engine must fall back to the per-symbol
    # flow and still match the oracle
    reads = _dual_reads(200, 4)
    cfgs = (
        _cfg("native", 0.25, weighted_by_ed=True),
        _cfg("jax", 0.25, weighted_by_ed=True),
    )
    oracle = native_dual_consensus(reads, config=cfgs[0])
    engine = DualConsensusDWFA(cfgs[1])
    for r in reads:
        engine.add_sequence(r)
    assert engine.consensus() == oracle
