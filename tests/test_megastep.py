"""MEGASTEP parity fuzz: the device-resident run-until-ambiguous path
(``WAFFLE_MEGASTEP``, ``run_extend(..., mega=True)``) must be
byte-identical to plain stepping on every engine, at every exit
reason, and under every knob combination — the megastep composes the
SAME masked per-column substep M×K times per device iteration, so any
divergence is a correctness bug, not a tuning artifact.

Families:

* engine-level fuzz (single / dual / priority) mega-on vs mega-off vs
  the python oracle, across seeds and error rates that traverse the
  ambiguity classes (clean runs, dirty-vote forks, record absorption);
* M×K composition: ``WAFFLE_MEGA_BLOCKS`` x ``WAFFLE_RUN_COLS`` in
  {1,4}x{1,4} — block composition must not move a single commit;
* forced-i16 band state (``WAFFLE_XLA_I16=1``) under mega;
* mid-megastep stop codes: a tiny ``WAFFLE_MEGA_SYMS`` budget caps
  every dispatch mid-run (stop code 4) and the engine re-engages from
  the partial trail;
* band overflow (stop code 5) mid-megastep via a deliberately small
  ``initial_band``;
* the capability seam: ``run_mega`` is property-gated (None when
  ``WAFFLE_MEGASTEP=0``), survives ``fast_paths`` snapshots, and the
  supervisor retries a faulted megastep as plain stepping without
  demotion;
* the point of it all: strictly fewer blocking host round trips per
  search than plain stepping, with the commit trail unchanged.
"""

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.models.priority_consensus import PriorityConsensusDWFA
from waffle_con_tpu.utils.example_gen import corrupt, generate_test

# ------------------------------------------------------------ helpers


def _cfg(backend, min_count=2, **over):
    b = CdwfaConfigBuilder().backend(backend).min_count(min_count)
    for k, v in over.items():
        b = getattr(b, k)(v)
    return b.build()


def _set_mega(monkeypatch, mega, cols="1", blocks="1", syms=None,
              i16=None):
    monkeypatch.setenv("WAFFLE_MEGASTEP", "1" if mega else "0")
    monkeypatch.setenv("WAFFLE_RUN_COLS", cols)
    monkeypatch.setenv("WAFFLE_MEGA_BLOCKS", blocks)
    if syms is not None:
        monkeypatch.setenv("WAFFLE_MEGA_SYMS", syms)
    if i16 is not None:
        monkeypatch.setenv("WAFFLE_XLA_I16", i16)


def _single(reads, backend="jax", min_count=2, **over):
    e = ConsensusDWFA(_cfg(backend, min_count, **over))
    for r in reads:
        e.add_sequence(r)
    res = [(c.sequence, c.scores) for c in e.consensus()]
    return res, dict(e.last_search_stats.get("scorer_counters", {}))


def _dual(reads, backend="jax", min_count=2):
    e = DualConsensusDWFA(_cfg(backend, min_count))
    for r in reads:
        e.add_sequence(r)
    return e.consensus(), dict(
        e.last_search_stats.get("scorer_counters", {})
    )


def _dual_reads(seq_len=80, n_per=4, er=0.03, seed=4000):
    rng = np.random.default_rng(seed)
    truth, reads1 = generate_test(4, seq_len, n_per, er, seed=seed + 1)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=2, replace=False):
        h2[pos] = (h2[pos] + 1 + int(rng.integers(3))) % 4
    return list(reads1) + [
        corrupt(bytes(h2), er, np.random.default_rng(seed + 2 + i))
        for i in range(n_per)
    ]


def _chains(n=6, seed=5000):
    _, level0 = generate_test(4, 40, n, 0.02, seed=seed)
    t1a, _ = generate_test(4, 70, 1, 0.0, seed=seed + 1)
    t1b = bytearray(t1a)
    t1b[35] = (t1b[35] + 1) % 4
    t1b = bytes(t1b)
    return [
        [level0[i],
         corrupt(t1a if i < n // 2 else t1b, 0.02,
                 np.random.default_rng(seed + 2 + i))]
        for i in range(n)
    ]


# ------------------------------------------------ engine-level parity


@pytest.mark.parametrize("seed", [1, 2, 3])
@pytest.mark.parametrize("er,min_count", [(0.02, 2), (0.08, 3)])
def test_single_exit_reason_fuzz(seed, er, min_count, monkeypatch):
    """Mega-on == mega-off == python oracle across workloads spanning
    the ambiguity spectrum: 2% error barely forks (long unambiguous
    runs — the megastep's best case), 8% at min_count 3 forks
    constantly (the megastep exits at nearly every pop — its worst
    case).  Both must commit the identical trail."""
    _, reads = generate_test(4, 90, 6, er, seed=seed)
    _set_mega(monkeypatch, False)
    plain, _ = _single(reads, min_count=min_count)
    _set_mega(monkeypatch, True, cols="2", blocks="4")
    mega, counters = _single(reads, min_count=min_count)
    assert mega == plain
    assert counters.get("run_mega_calls", 0) > 0
    oracle, _ = _single(reads, backend="python", min_count=min_count)
    assert mega == oracle
    # the fuzz family must actually traverse host-arbitration exits
    # (stop code 1 = dirty vote / fork), not just clean completions
    assert counters.get("run_stop_1", 0) > 0


@pytest.mark.parametrize("m", ["1", "4"])
@pytest.mark.parametrize("k", ["1", "4"])
def test_mk_composition_fuzz(m, k, monkeypatch):
    """M blocks x K columns composition: every (M, K) pairing commits
    the same bytes as plain K=1 stepping."""
    _, reads = generate_test(4, 100, 6, 0.04, seed=11)
    _set_mega(monkeypatch, False, cols="1")
    plain, _ = _single(reads)
    _set_mega(monkeypatch, True, cols=k, blocks=m)
    mega, counters = _single(reads)
    assert mega == plain
    assert counters.get("run_mega_calls", 0) > 0


@pytest.mark.parametrize("seed", [21, 22])
def test_forced_i16_mega_fuzz(seed, monkeypatch):
    """Forced 16-bit band state under the megastep: the saturating
    arithmetic swap must stay invisible through M x K composition."""
    _, reads = generate_test(4, 80, 6, 0.05, seed=seed)
    _set_mega(monkeypatch, False)
    monkeypatch.delenv("WAFFLE_XLA_I16", raising=False)
    plain, _ = _single(reads)
    _set_mega(monkeypatch, True, cols="2", blocks="4", i16="1")
    mega, _ = _single(reads)
    assert mega == plain


@pytest.mark.parametrize("syms", ["1", "3", "7"])
def test_mid_megastep_stop_codes(syms, monkeypatch):
    """A tiny per-dispatch commit budget forces every megastep to cap
    mid-run (stop code 4): the engine must re-engage from the partial
    trail and still finish byte-identical, with the cap visible as
    strictly more mega dispatches than the uncapped path takes."""
    _, reads = generate_test(4, 60, 6, 0.02, seed=31)
    _set_mega(monkeypatch, False)
    plain, _ = _single(reads)
    _set_mega(monkeypatch, True, cols="2", blocks="2", syms=syms)
    mega, counters = _single(reads)
    assert mega == plain
    assert counters.get("run_stop_4", 0) > 0
    assert counters.get("run_mega_calls", 0) >= 60 // int(syms)


def test_band_overflow_mid_megastep(monkeypatch):
    """Stop code 5 (band overflow) inside a megastep: the engine grows
    the band and replays, landing on the same bytes as plain stepping
    with the same growth path."""
    _, reads = generate_test(4, 80, 6, 0.06, seed=41)
    _set_mega(monkeypatch, False)
    plain, c_plain = _single(reads, initial_band=2)
    _set_mega(monkeypatch, True, cols="2", blocks="4")
    mega, c_mega = _single(reads, initial_band=2)
    assert mega == plain
    assert c_mega.get("grow_e_events", 0) > 0
    assert c_mega.get("grow_e_events") == c_plain.get("grow_e_events")


def test_dual_mega_parity(monkeypatch):
    reads = _dual_reads()
    _set_mega(monkeypatch, False)
    plain, _ = _dual(reads)
    _set_mega(monkeypatch, True, cols="2", blocks="4")
    mega, counters = _dual(reads)
    assert mega == plain
    assert counters.get("run_mega_calls", 0) > 0


def test_priority_mega_parity(monkeypatch):
    """Priority chains drive the megastep through SubsetScorer (the
    per-group read-slice adapter), so this doubles as the slicing
    parity check for ``run_mega``."""
    chains = _chains()

    def run():
        e = PriorityConsensusDWFA(_cfg("jax"))
        for c in chains:
            e.add_sequence_chain(c)
        return e.consensus()

    _set_mega(monkeypatch, False)
    plain = run()
    _set_mega(monkeypatch, True, cols="2", blocks="4")
    mega = run()
    assert mega == plain


# ------------------------------------------------ capability gating


def test_run_mega_property_gated(monkeypatch):
    from waffle_con_tpu.ops.jax_scorer import JaxScorer
    from waffle_con_tpu.ops.scorer import fast_paths, megastep_enabled

    _, reads = generate_test(4, 40, 4, 0.02, seed=51)
    scorer = JaxScorer(list(reads), _cfg("jax"))
    monkeypatch.setenv("WAFFLE_MEGASTEP", "0")
    assert not megastep_enabled()
    assert scorer.run_mega is None
    assert fast_paths(scorer).run_mega is None
    monkeypatch.setenv("WAFFLE_MEGASTEP", "1")
    assert megastep_enabled()
    assert scorer.run_mega is not None
    # fast_paths snapshots are cached on the scorer instance (keyed by
    # the supervisor's demotion generation, not the env), so the flip
    # is seen by a FRESH scorer — the engines build one per search
    fresh = JaxScorer(list(reads), _cfg("jax"))
    assert fast_paths(fresh).run_mega is not None


def test_mega_reduces_host_round_trips(monkeypatch):
    """The megastep's reason to exist, asserted at engine level: the
    SAME search pays strictly fewer blocking host syncs with mega on,
    and commits the identical trail."""
    _, reads = generate_test(4, 120, 6, 0.01, seed=61)
    _set_mega(monkeypatch, False)
    plain, c_plain = _single(reads)
    _set_mega(monkeypatch, True, cols="2", blocks="4")
    mega, c_mega = _single(reads)
    assert mega == plain
    assert c_mega.get("run_mega_calls", 0) > 0
    assert c_mega["host_round_trips"] < c_plain["host_round_trips"]


def test_supervisor_retries_megastep_as_plain(faults, monkeypatch):
    """A megastep dispatch whose RESULT fails validation (garbage
    fault — fires after the kernel ran, like a real mid-megastep
    failure) must be retried by the supervisor as PLAIN stepping (the
    conservative path), without demoting the backend, and finish
    byte-identical."""
    from waffle_con_tpu.runtime import events

    _set_mega(monkeypatch, True, cols="2", blocks="2")
    _, reads = generate_test(4, 60, 5, 0.02, seed=71)

    def run(cfg):
        e = ConsensusDWFA(cfg)
        for r in reads:
            e.add_sequence(r)
        return [(c.sequence, c.scores) for c in e.consensus()]

    expected = run(_cfg("jax"))
    faults.add("garbage", backend="jax", op="run", count=1)
    got = run(_cfg(
        "jax", backend_chain=("python",), dispatch_retries=1,
        breaker_threshold=3, retry_backoff_s=0.0,
    ))
    assert got == expected
    assert events.get_events("backend_demoted") == []
    failed = [
        e for e in events.get_events("dispatch_failed")
        if e.get("op") == "run"
    ]
    assert failed, "injected run-result fault never surfaced"
