"""End-to-end tests for the priority (chained multi) consensus engine,
mirroring ``/root/reference/src/priority_consensus.rs:357-655``."""

import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    Consensus,
    ConsensusCost,
    PriorityConsensus,
    PriorityConsensusDWFA,
)
from waffle_con_tpu.models.consensus import EngineError
from waffle_con_tpu.utils.fixtures import load_priority_fixture


def run_fixture(name, include_consensus, config=None):
    if config is None:
        config = CdwfaConfigBuilder().wildcard(ord("*")).build()
    chains, expected = load_priority_fixture(
        name, include_consensus, config.consensus_cost
    )
    engine = PriorityConsensusDWFA(config)
    for chain in chains:
        engine.add_sequence_chain(chain)
    assert len(engine.alphabet) == 4
    result = engine.consensus()
    assert result.sequence_indices == expected.sequence_indices
    assert len(result.consensuses) == len(expected.consensuses)
    for got_chain, want_chain in zip(result.consensuses, expected.consensuses):
        assert len(got_chain) == len(want_chain)
        for got, want in zip(got_chain, want_chain):
            assert got.sequence == want.sequence


def test_single_sequence():
    sequence = b"ACGTACGTACGT"
    engine = PriorityConsensusDWFA()
    engine.add_sequence_chain([sequence, sequence])
    assert len(engine.alphabet) == 4
    assert engine.consensus() == PriorityConsensus(
        [[Consensus(sequence, ConsensusCost.L1_DISTANCE, [0])] * 2],
        [0],
    )


def test_doc_example():
    chains = (
        [[b"TCCGT", b"TCCGT"]] * 3
        + [[b"TCCGT", b"ACGGT"]] * 3
        + [[b"ACGT", b"ACCCGGTT"]] * 3
    )
    engine = PriorityConsensusDWFA()
    for chain in chains:
        engine.add_sequence_chain(chain)
    result = engine.consensus()
    assert result.consensuses == [
        [
            Consensus(b"ACGT", ConsensusCost.L1_DISTANCE, [0] * 3),
            Consensus(b"ACCCGGTT", ConsensusCost.L1_DISTANCE, [0] * 3),
        ],
        [
            Consensus(b"TCCGT", ConsensusCost.L1_DISTANCE, [0] * 6),
            Consensus(b"ACGGT", ConsensusCost.L1_DISTANCE, [0] * 3),
        ],
        [
            Consensus(b"TCCGT", ConsensusCost.L1_DISTANCE, [0] * 6),
            Consensus(b"TCCGT", ConsensusCost.L1_DISTANCE, [0] * 3),
        ],
    ]
    assert result.sequence_indices == [2, 2, 2, 1, 1, 1, 0, 0, 0]


def test_chain_length_mismatch():
    engine = PriorityConsensusDWFA()
    engine.add_sequence_chain([b"ACGT", b"ACGT"])
    with pytest.raises(EngineError):
        engine.add_sequence_chain([b"ACGT"])
    with pytest.raises(EngineError):
        engine.add_sequence_chain([])


def test_seeded_groups():
    # seeds force an initial partition even when sequences agree
    chains = [[b"ACGTACGT"]] * 6
    engine = PriorityConsensusDWFA()
    for i, chain in enumerate(chains):
        engine.add_seeded_sequence_chain(chain, [None], i % 2)
    result = engine.consensus()
    assert len(result.consensuses) == 2
    assert all(c[0].sequence == b"ACGTACGT" for c in result.consensuses)


# fixture scenarios shared with the dual engine
def test_csv_dual_001():
    run_fixture("dual_001", True)


def test_multi_exact_001():
    run_fixture("multi_exact_001", True)


def test_multi_exact_002():
    run_fixture("multi_exact_002", True)


def test_multi_err_001():
    run_fixture("multi_err_001", False)


def test_multi_err_002():
    run_fixture("multi_err_002", False)


def test_multi_samesplit_001():
    # four reads with a unique symbol at one position: 4-way split
    run_fixture("multi_samesplit_001", True)


def test_multi_postcon_001():
    # the split works but the group needs a re-polish to find its best
    # consensus
    run_fixture(
        "multi_postcon_001",
        True,
        CdwfaConfigBuilder().wildcard(ord("*")).min_count(2).build(),
    )


def test_priority_001():
    run_fixture("priority_001", True)


def test_priority_002():
    run_fixture("priority_002", True)


def test_priority_003():
    run_fixture("priority_003", True)


def test_jax_backend_shares_one_scorer_per_level():
    """VERDICT r3 #4: on the jax backend the priority engine must build
    ONE device scorer per chain level per consensus() call, sharing it
    across every worklist group via SubsetScorer views."""
    cfg = CdwfaConfigBuilder().min_count(1).backend("jax").build()
    engine = PriorityConsensusDWFA(cfg)
    # two levels; level 1 splits into two groups -> 3 dual runs at least
    chains = [
        [b"ACGTACGT", b"AAAACCCC"],
        [b"ACGTACGT", b"AAAACCCC"],
        [b"ACGTACGT", b"GGGGTTTT"],
        [b"ACGTACGT", b"GGGGTTTT"],
    ]
    for chain in chains:
        engine.add_sequence_chain(chain)
    result = engine.consensus()
    assert len(result.consensuses) == 2
    stats = engine.last_search_stats
    assert stats["scorer_constructions"] == 2  # == number of levels
    counters = stats["scorer_counters"]
    # expansions flow through either the plain push or the fused
    # clone+push dispatch, depending on which fast paths engaged
    assert (
        counters.get("push_calls", 0) + counters.get("clone_push_calls", 0)
    ) > 0
