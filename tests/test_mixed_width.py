"""Mixed-width ragged arena: width-agnostic pages, in-pool
re-centering, and learned placement.

Core claims under test: (1) gang members with *different* band widths
run through one stride-masked ragged kernel byte-identical to their
solo ``run_extend`` paths, at the serve layer (all three engines) and
at the kernel seam directly; (2) a band grow (E doubling) re-centers a
resident member in pool — it keeps ganging at its new per-row stride —
while a width outgrowing the pool evicts cleanly; (3) exhaustion /
degradation semantics are unchanged by stride-mixed page runs; (4)
frontier gangs of heterogeneous-W searches stay byte-identical to
M=1; (5) learned placement follows perfdb substrate medians when the
history is warm and falls back to the static read-count threshold when
cold, one-sided, or disabled.
"""

import numpy as np
import pytest

from waffle_con_tpu import CdwfaConfigBuilder, ConsensusDWFA
from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import perfdb
from waffle_con_tpu.ops import ragged
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.serve import (
    ConsensusService,
    JobRequest,
    ServeConfig,
)
from waffle_con_tpu.serve import placement
from waffle_con_tpu.serve.placement import PlacementPolicy
from waffle_con_tpu.serve.service import _build_engine
from waffle_con_tpu.utils.example_gen import generate_test
from waffle_con_tpu.utils.fixtures import (
    load_dual_fixture,
    load_priority_fixture,
)

pytestmark = pytest.mark.serve

BIG = 10**9

#: band seeds landing on three distinct pow2 E geometries under the
#: default pool (E=32): E 8 / 16 / 32 -> natural W 18 / 34 / 66
BAND_SEEDS = (8, 12, 24)


@pytest.fixture
def arena_env(monkeypatch):
    monkeypatch.setenv("WAFFLE_RAGGED", "1")
    ragged.reset_arena()
    yield
    ragged.reset_arena()


def _jax_cfg(band=None, **kw):
    b = CdwfaConfigBuilder().backend("jax")
    if band is not None:
        b = b.initial_band(band)
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _band_cfg(band):
    return CdwfaConfig(initial_band=band)


# ------------------------------------------------- serve-layer parity


def _mixed_width_requests():
    """Nine jax jobs across all three engines, band seeds cycling
    through three distinct pow2 E geometries — only the stride-masked
    kernel can gang them."""
    requests = []
    fcfg = _jax_cfg(band=BAND_SEEDS[0], min_count=2)
    sequences, _ = load_dual_fixture("dual_001", True, fcfg.consensus_cost)
    requests.append(
        JobRequest(kind="dual", reads=tuple(sequences), config=fcfg)
    )
    chains, _ = load_priority_fixture(
        "priority_001", True, fcfg.consensus_cost
    )
    requests.append(
        JobRequest(
            kind="priority",
            reads=tuple(tuple(c) for c in chains),
            config=_jax_cfg(band=BAND_SEEDS[1], min_count=2),
        )
    )
    shapes = [(4, 90), (7, 140), (3, 60), (10, 200), (5, 120),
              (6, 180), (8, 100)]
    for seed, (n, length) in enumerate(shapes):
        _, reads = generate_test(n, length, 6, 0.02, seed=seed)
        cfg = _jax_cfg(
            band=BAND_SEEDS[seed % 3], min_count=max(2, n // 4)
        )
        requests.append(
            JobRequest(kind="single", reads=tuple(reads), config=cfg)
        )
    return requests


def test_mixed_width_serve_parity_all_engines(arena_env):
    requests = _mixed_width_requests()
    expected = [_build_engine(r).consensus() for r in requests]

    with ConsensusService(
        ServeConfig(workers=8, batch_window_s=0.05, max_batch=8)
    ) as svc:
        handles = svc.submit_all(requests)
        results = [h.result(timeout=300) for h in handles]
        stats = svc.stats()

    for got, want in zip(results, expected):
        assert got == want, "mixed-W served job diverged from serial"
    assert stats["jobs"]["failed"] == 0

    arena = stats["ragged"]
    assert arena["mixed_w"] is True
    assert arena["groups"] >= 1
    assert arena["members"] >= 2
    assert arena["pages_used"] == 0
    assert arena["member_store_failures"] == 0


# ------------------------------------------------ direct kernel parity


def _mutated_reads(n, lo, hi, seed):
    r = np.random.default_rng(seed)
    base = r.integers(0, 4, size=int(r.integers(lo, hi))).astype(np.uint8)
    reads = []
    for _ in range(n):
        b = base.copy()
        m = r.random(len(b)) < 0.03
        b[m] = r.integers(0, 4, int(m.sum())).astype(np.uint8)
        reads.append(bytes(b))
    return reads


def _parity_rounds(solos, rags, jobs, rounds, max_steps=8):
    """Drive ``rounds`` lockstep run_extend rounds through the gang and
    the solo path, asserting byte/stats equality each round."""
    hs_s = [s.root(np.ones(len(j), bool)) for s, j in zip(solos, jobs)]
    hs_r = [s.root(np.ones(len(j), bool)) for s, j in zip(rags, jobs)]
    cons_s = [b""] * len(jobs)
    cons_r = [b""] * len(jobs)
    for rnd in range(rounds):
        solo_out = [
            s.run_extend(h, c, BIG, BIG, 0, 2, False, max_steps,
                         allow_records=False)
            for s, h, c in zip(solos, hs_s, cons_s)
        ]
        args_list = [
            (h, c, BIG, BIG, 0, 2, False, max_steps)
            for h, c in zip(hs_r, cons_r)
        ]
        specs = []
        for s, a in zip(rags, args_list):
            spec = ragged.probe((s.ragged_run_probe, a, {}))
            assert spec is not None, "eligible mixed-W member refused"
            specs.append(spec)
        ragged.run_group(specs)
        rag_out = [s.run_extend(*a) for s, a in zip(rags, args_list)]
        for g, (so, ro) in enumerate(zip(solo_out, rag_out)):
            s_steps, s_code, s_app, s_stats, s_rec = so
            r_steps, r_code, r_app, r_stats, r_rec = ro
            ctx = f"round {rnd} job {g}"
            assert (s_steps, s_code, s_app) == (r_steps, r_code, r_app), ctx
            assert s_rec == [] and r_rec == []
            np.testing.assert_array_equal(s_stats.eds, r_stats.eds, ctx)
            np.testing.assert_array_equal(s_stats.occ, r_stats.occ, ctx)
            np.testing.assert_array_equal(s_stats.split, r_stats.split, ctx)
            np.testing.assert_array_equal(
                s_stats.reached, r_stats.reached, ctx
            )
            if s_stats.fin is None:
                assert r_stats.fin is None, ctx
            else:
                np.testing.assert_array_equal(s_stats.fin, r_stats.fin, ctx)
            cons_s[g] += s_app
            cons_r[g] += r_app


def test_mixed_width_kernel_matches_solo(arena_env):
    """Three members at three distinct band widths gang through one
    stride-masked kernel call per round, byte/stats-identical to
    solo."""
    jobs = [
        _mutated_reads(5, 80, 120, 1),
        _mutated_reads(9, 150, 200, 2),
        _mutated_reads(3, 40, 60, 3),
    ]
    solos = [JaxScorer(r, _band_cfg(b)) for r, b in zip(jobs, BAND_SEEDS)]
    rags = [JaxScorer(r, _band_cfg(b)) for r, b in zip(jobs, BAND_SEEDS)]
    widths = sorted(s._W for s in rags)
    assert len(set(widths)) == 3, widths  # genuinely heterogeneous

    _parity_rounds(solos, rags, jobs, rounds=4)

    arena = ragged.get_arena()
    st = arena.stats()
    assert st["groups"] == 4
    assert st["mean_occupancy"] == 3.0
    assert st["mixed_w_groups"] == 4
    # gang_rows counts the staged pool rows actually stepped (page runs
    # include the scorers' pow2 row padding, so >= the raw read count)
    assert st["gang_rows"] >= 4 * sum(len(j) for j in jobs)
    assert st["mean_gang_rows"] == st["gang_rows"] / 4
    for s in rags:
        s.ragged_release()
    assert arena.stats()["pages_used"] == 0


def test_mixed_w_disabled_restores_equality_gate(arena_env, monkeypatch):
    monkeypatch.setenv("WAFFLE_RAGGED_MIXED_W", "0")
    ragged.reset_arena()
    reads = _mutated_reads(4, 60, 90, 7)
    narrow = JaxScorer(reads, _band_cfg(8))    # W=18 != pool W
    matched = JaxScorer(reads, _band_cfg(24))  # W=66 == pool W (E=32)
    arena = ragged.get_arena()
    assert narrow._W != arena.W and matched._W == arena.W
    h_n = narrow.root(np.ones(4, bool))
    h_m = matched.root(np.ones(4, bool))
    args = (h_n, b"", BIG, BIG, 0, 2, False, 8)
    assert ragged.probe((narrow.ragged_run_probe, args, {})) is None
    args = (h_m, b"", BIG, BIG, 0, 2, False, 8)
    assert ragged.probe((matched.ragged_run_probe, args, {})) is not None
    matched.ragged_release()


# --------------------------------------------- re-centering under growth


def test_recenter_under_growth_keeps_parity(arena_env):
    """Doubling a resident member's band re-centers it in pool: it
    keeps ganging at the new stride and stays byte-identical to a solo
    scorer taken through the same growth."""
    obs_metrics.enable_metrics(True)
    obs_metrics.registry().reset()
    try:
        jobs = [
            _mutated_reads(4, 70, 100, 11),
            _mutated_reads(6, 120, 160, 12),
        ]
        bands = (8, 24)  # W 18 and 66
        solos = [JaxScorer(r, _band_cfg(b)) for r, b in zip(jobs, bands)]
        rags = [JaxScorer(r, _band_cfg(b)) for r, b in zip(jobs, bands)]

        _parity_rounds(solos, rags, jobs, rounds=2)
        arena = ragged.get_arena()
        assert arena.stats()["groups"] == 2

        # grow the narrow member on BOTH paths (E 8 -> 16, W 18 -> 34,
        # still under the pool's 66): residency must survive
        solos[0]._grow_e()
        rags[0]._grow_e()
        assert rags[0]._W == 34
        st = arena.stats()
        assert st["recenters"] == 1
        assert st["releases"] == 0

        _parity_rounds(solos, rags, jobs, rounds=2)
        st = arena.stats()
        assert st["groups"] == 4  # the grown member ganged again
        assert st["mixed_w_groups"] == 4

        snap = obs_metrics.registry().snapshot()
        series = snap["waffle_ragged_recenter_total"]["series"]
        assert sum(series.values()) == 1
        for s in rags:
            s.ragged_release()
    finally:
        obs_metrics.reset_metrics_enabled()
        obs_metrics.registry().reset()


def test_recenter_evicts_when_band_outgrows_pool(arena_env, monkeypatch):
    monkeypatch.setenv("WAFFLE_RAGGED_E", "8")  # pool W = 18
    ragged.reset_arena()
    reads = _mutated_reads(4, 60, 90, 13)
    s = JaxScorer(reads, _band_cfg(8))  # W = 18 == pool W
    arena = ragged.get_arena()
    assert arena.try_admit(s, job_id=1) is not None
    assert arena.stats()["pages_used"] > 0

    s._grow_e()  # W 18 -> 34 > pool's 18: classic eviction
    st = arena.stats()
    assert st["recenters"] == 0
    assert st["releases"] == 1
    assert st["pages_used"] == 0
    # and the grown scorer is no longer gang-eligible
    h = s.root(np.ones(4, bool))
    args = (h, b"", BIG, BIG, 0, 2, False, 8)
    assert ragged.probe((s.ragged_run_probe, args, {})) is None


# ------------------------------------------- exhaustion with mixed runs


def test_exhaustion_degrades_with_mixed_width_runs(arena_env, monkeypatch):
    monkeypatch.setenv("WAFFLE_RAGGED_ROWS", "16")
    monkeypatch.setenv("WAFFLE_RAGGED_PAGE", "8")
    ragged.reset_arena()
    _, reads = generate_test(8, 60, 6, 0.02, seed=21)
    scorers = [
        JaxScorer(tuple(reads), _band_cfg(b)) for b in (8, 24, 12)
    ]
    arena = ragged.get_arena()
    assert arena.try_admit(scorers[0], job_id=1) is not None
    assert arena.try_admit(scorers[1], job_id=2) is not None
    assert arena.try_admit(scorers[2], job_id=3) is None  # pool full
    assert arena.stats()["exhausted"] == 1

    # releasing the wide member recycles its pages to the waiting one
    arena.release_scorer(scorers[1])
    assert arena.try_admit(scorers[2], job_id=3) is not None
    arena.release_job(1)
    arena.release_scorer(scorers[2])
    st = arena.stats()
    assert st["pages_used"] == 0
    assert st["pages_free"] == st["pages_total"]


def test_tiny_pool_mixed_width_serve_still_byte_identical(
    arena_env, monkeypatch
):
    monkeypatch.setenv("WAFFLE_RAGGED_ROWS", "8")
    monkeypatch.setenv("WAFFLE_RAGGED_PAGE", "8")
    ragged.reset_arena()
    requests = _mixed_width_requests()[2:6]
    expected = [_build_engine(r).consensus() for r in requests]
    with ConsensusService(
        ServeConfig(workers=4, batch_window_s=0.02, max_batch=8)
    ) as svc:
        handles = svc.submit_all(requests)
        results = [h.result(timeout=300) for h in handles]
    assert results == expected


# ---------------------------------------- frontier gang, heterogeneous W


def _frontier_consensus(reads, m, band, monkeypatch):
    monkeypatch.setenv("WAFFLE_FRONTIER_M", str(m))
    engine = ConsensusDWFA(_jax_cfg(band=band, min_count=2))
    for r in reads:
        engine.add_sequence(r)
    result = [(c.sequence, c.scores) for c in engine.consensus()]
    counters = dict(
        engine.last_search_stats.get("scorer_counters", {})
    )
    return result, counters


def test_frontier_gang_heterogeneous_w_peers(monkeypatch):
    """Two searches with different natural band widths both speculate
    through the shared kernel closure in one process, each
    byte-identical to its M=1 run."""
    workloads = []
    for band, seed in ((8, 52300), (24, 52400)):
        _, reads = generate_test(4, 300, 8, 0.02, seed=seed)
        workloads.append((band, reads))
    ganged_any = 0
    for band, reads in workloads:
        base, _ = _frontier_consensus(reads, 1, band, monkeypatch)
        ganged, counters = _frontier_consensus(reads, 3, band, monkeypatch)
        assert ganged == base, f"band {band} diverged under M=3"
        ganged_any += counters.get("gang_groups", 0)
    assert ganged_any >= 1  # speculation actually fired at some width


# -------------------------------------------------- learned placement


def _jax_request(n_reads):
    return JobRequest(
        kind="single",
        reads=tuple(b"ACGTACGT" for _ in range(n_reads)),
        config=_jax_cfg(),
    )


@pytest.fixture
def learned_env(monkeypatch, tmp_path):
    monkeypatch.setenv("WAFFLE_PERFDB", str(tmp_path / "perfdb.jsonl"))
    monkeypatch.setenv("WAFFLE_PLACEMENT_LEARNED", "1")
    placement.reset_profile_cache()
    yield
    placement.reset_profile_cache()


def test_learned_placement_cold_falls_back_to_threshold(learned_env):
    pol = PlacementPolicy(large_read_threshold=64)
    assert pol.classify(_jax_request(100)) == "mesh"
    assert pol.classify(_jax_request(10)) == "arena"


def test_learned_placement_warm_overrides_threshold(learned_env):
    pol = PlacementPolicy(large_read_threshold=64)
    # warm history says arena beats mesh for the 128-reads bucket
    for _ in range(placement.MIN_PROFILE_SAMPLES):
        placement.record_outcome("mesh", 100, 2.0)
        placement.record_outcome("arena", 100, 0.5)
    assert pol.classify(_jax_request(100)) == "arena"
    # …but the 16-reads bucket stays cold: static threshold applies
    assert pol.classify(_jax_request(10)) == "arena"
    # flip the history: mesh now faster — the stamp change re-reads
    for _ in range(2 * placement.MIN_PROFILE_SAMPLES):
        placement.record_outcome("mesh", 100, 0.1)
    assert pol.classify(_jax_request(100)) == "mesh"


def test_learned_placement_one_sided_history_is_cold(learned_env):
    pol = PlacementPolicy(large_read_threshold=64)
    for _ in range(5 * placement.MIN_PROFILE_SAMPLES):
        placement.record_outcome("arena", 100, 0.1)
    # no mesh samples at all: never learned, threshold decides
    assert pol.classify(_jax_request(100)) == "mesh"


def test_learned_placement_disabled_ignores_history(
    learned_env, monkeypatch
):
    for _ in range(placement.MIN_PROFILE_SAMPLES):
        placement.record_outcome("mesh", 100, 2.0)
        placement.record_outcome("arena", 100, 0.5)
    monkeypatch.setenv("WAFFLE_PLACEMENT_LEARNED", "0")
    pol = PlacementPolicy(large_read_threshold=64)
    assert pol.classify(_jax_request(100)) == "mesh"


def test_learned_placement_prefers_phase_profile_seconds(learned_env):
    pol = PlacementPolicy(large_read_threshold=64)
    # wall says mesh is slower, but the attributable phase time
    # (host+device+transfer) says mesh is faster — phases win
    for _ in range(placement.MIN_PROFILE_SAMPLES):
        placement.record_outcome(
            "mesh", 100, 9.0,
            phases={"host_prep": 0.05, "device_compute": 0.1,
                    "transfer": 0.05},
        )
        placement.record_outcome("arena", 100, 0.5)
    assert pol.classify(_jax_request(100)) == "mesh"


def test_service_records_placement_profiles(learned_env, arena_env):
    """With the knob on, every done job appends one placement_profile
    record carrying its substrate and reads bucket."""
    requests = _mixed_width_requests()[2:5]
    with ConsensusService(
        ServeConfig(workers=2, batch_window_s=0.02, max_batch=8)
    ) as svc:
        handles = svc.submit_all(requests)
        for h in handles:
            h.result(timeout=300)
    records = perfdb.load_records(kind=perfdb.PLACEMENT_KIND)
    assert len(records) == len(requests)
    for rec, req in zip(
        sorted(records, key=lambda r: r["n_reads"]),
        sorted(requests, key=lambda r: len(r.reads)),
    ):
        assert rec["substrate"] == "arena"  # no policy: nothing meshed
        assert rec["n_reads"] == len(req.reads)
        assert rec["reads_bucket"] == perfdb.reads_bucket(len(req.reads))
        assert rec["value"] > 0
