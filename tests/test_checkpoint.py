"""Serializable search checkpoints: snapshot → JSON → resume must be
byte-identical to the uninterrupted search, on every backend, at any
pop boundary — including mid-gang (``WAFFLE_FRONTIER_M`` > 1) and
mid-K-block (``WAFFLE_RUN_COLS`` > 1), because the snapshot stores
only the node-identity tuples ``(consensus, active, offsets)`` and the
restore rebuilds branches through the ordinary ``root``/``push``/
``activate`` dispatch seam.  Corrupt, truncated, version-skewed, or
wrong-engine payloads must raise typed :class:`CheckpointRejected`
(the stored priorities double as an integrity check on the rebuilt
nodes), and the serving layer must degrade a rejected checkpoint to a
from-scratch search — never a failed or hung job."""

import json

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
    PriorityConsensusDWFA,
)
from waffle_con_tpu.models import checkpoint as ckpt_mod
from waffle_con_tpu.utils.example_gen import corrupt, generate_test

# ------------------------------------------------------------ workloads


def _single_reads():
    _, reads = generate_test(4, 100, 8, 0.03, seed=52300)
    return list(reads)


def _dual_reads():
    # kept small: the dual engine pays per-column dispatch for two
    # consensuses, and the jax matrix runs this at K=1
    rng = np.random.default_rng(61250)
    truth, reads1 = generate_test(4, 60, 3, 0.04, seed=61251)
    h2 = bytearray(truth)
    for pos in rng.choice(60, size=2, replace=False):
        h2[pos] = (h2[pos] + 1 + int(rng.integers(3))) % 4
    return list(reads1) + [
        corrupt(bytes(h2), 0.04, np.random.default_rng(61252 + i))
        for i in range(3)
    ]


def _chains():
    n = 6
    _, level0 = generate_test(4, 50, n, 0.02, seed=71000)
    t1a, _ = generate_test(4, 80, 1, 0.0, seed=71001)
    t1b = bytearray(t1a)
    t1b[40] = (t1b[40] + 1) % 4
    t1b = bytes(t1b)
    return [
        [level0[i],
         corrupt(t1a if i < n // 2 else t1b, 0.02,
                 np.random.default_rng(71002 + i))]
        for i in range(n)
    ]


def _cfg(backend, min_count=2):
    return (
        CdwfaConfigBuilder().backend(backend).min_count(min_count).build()
    )


def _make_engine(kind, backend):
    if kind == "single":
        engine = ConsensusDWFA(_cfg(backend))
        for read in _single_reads():
            engine.add_sequence(read)
    elif kind == "dual":
        engine = DualConsensusDWFA(_cfg(backend))
        for read in _dual_reads():
            engine.add_sequence(read)
    else:
        engine = PriorityConsensusDWFA(_cfg(backend))
        for chain in _chains():
            engine.add_sequence_chain(chain)
    return engine


def _run_with_snapshots(kind, backend):
    """Uninterrupted result + every pop-boundary snapshot along the way
    (interval ~0 => the controller snapshots at every poll)."""
    snaps = []
    ctrl = ckpt_mod.CheckpointController(
        interval_s=1e-9, on_snapshot=snaps.append
    )
    with ckpt_mod.installed(ctrl):
        ref = _make_engine(kind, backend).consensus()
    assert snaps, "search never reached a snapshot boundary"
    return ref, snaps


# python-oracle runs are M/K-independent and cheap relative to the jax
# matrix: compute each engine's reference + snapshot set once per module
_CACHE = {}


def _cached_snapshots(kind, backend):
    if (kind, backend) not in _CACHE:
        _CACHE[(kind, backend)] = _run_with_snapshots(kind, backend)
    return _CACHE[(kind, backend)]


def _resume(snapshot, extra_reads=()):
    """The full serialization loop a migration pays: wire dict → JSON
    text → wire dict → validated checkpoint → primed engine."""
    wire = json.loads(json.dumps(snapshot.to_wire()))
    checkpoint = ckpt_mod.SearchCheckpoint.from_wire(wire)
    return ckpt_mod.resume_engine(checkpoint, extra_reads=extra_reads)


# ------------------------------------------------- round-trip parity


@pytest.mark.parametrize("kind", ["single", "dual", "priority"])
def test_python_roundtrip_any_snapshot(kind):
    """Python oracle: resuming from the first, middle, and last
    snapshot all finish byte-identical to the uninterrupted search."""
    ref, snaps = _cached_snapshots(kind, "python")
    for idx in {0, len(snaps) // 2, len(snaps) - 1}:
        assert _resume(snaps[idx]).consensus() == ref, (
            f"{kind} resume from snapshot {idx}/{len(snaps)} diverged"
        )


@pytest.mark.parametrize("kind", ["single", "dual", "priority"])
@pytest.mark.parametrize("m", [1, 4])
@pytest.mark.parametrize("k", [1, 4])
def test_jax_roundtrip_mid_gang_mid_kblock(kind, m, k, monkeypatch):
    """Device backend: a mid-search snapshot taken while frontier gangs
    (M=4) and speculative K-blocks (K=4) are in flight resumes
    byte-identically — speculation is a pure cache, so it never leaks
    into (or out of) a checkpoint."""
    monkeypatch.setenv("WAFFLE_FRONTIER_M", str(m))
    monkeypatch.setenv("WAFFLE_RUN_COLS", str(k))
    ref = _cached_snapshots(kind, "python")[0]
    _jax_ref, snaps = _run_with_snapshots(kind, "jax")
    assert _jax_ref == ref, "jax diverged from the python oracle"
    assert _resume(snaps[len(snaps) // 2]).consensus() == ref


@pytest.mark.parametrize("kind", ["single", "dual", "priority"])
def test_jax_roundtrip_mid_megastep(kind, monkeypatch):
    """Device backend with the MEGASTEP path engaged and a tiny
    per-dispatch commit budget (``WAFFLE_MEGA_SYMS=7``): every megastep
    caps mid-run (stop code 4) and the engine re-engages from the
    partial trail, so snapshots land between megastep dispatches with
    multi-symbol committed stretches in flight.  A snapshot resolves at
    the megastep exit boundary — the device-committed trail is fully
    replayed into the node before the poll — so resume is
    byte-identical to the uninterrupted search."""
    monkeypatch.setenv("WAFFLE_MEGASTEP", "1")
    monkeypatch.setenv("WAFFLE_RUN_COLS", "4")
    monkeypatch.setenv("WAFFLE_MEGA_BLOCKS", "4")
    monkeypatch.setenv("WAFFLE_MEGA_SYMS", "7")
    ref = _cached_snapshots(kind, "python")[0]
    _jax_ref, snaps = _run_with_snapshots(kind, "jax")
    assert _jax_ref == ref, "jax megastep diverged from the python oracle"
    assert _resume(snaps[len(snaps) // 2]).consensus() == ref


@pytest.mark.parametrize("kind", ["single", "dual", "priority"])
def test_empty_extra_reads_is_plain_resume(kind):
    ref, snaps = _cached_snapshots(kind, "python")
    assert _resume(snaps[len(snaps) // 2], extra_reads=[]).consensus() \
        == ref


# ------------------------------------------------- incremental reads


def test_single_incremental_read_joins_mid_search():
    truth, _ = generate_test(4, 100, 8, 0.03, seed=52300)
    late = corrupt(truth, 0.03, np.random.default_rng(999))
    _ref, snaps = _cached_snapshots("single", "python")
    engine = _resume(snaps[len(snaps) // 2], extra_reads=[late])
    assert len(engine.sequences) == 9
    result = engine.consensus()
    assert result and all(len(c.sequence) > 0 for c in result)
    # the widened read set is scored: every result carries one score
    # per read, including the late one
    assert all(len(c.scores) == 9 for c in result)


def test_dual_extra_reads_pop0_only():
    _ref, snaps = _cached_snapshots("dual", "python")
    truth, _ = generate_test(4, 60, 3, 0.04, seed=61251)
    late = corrupt(truth, 0.04, np.random.default_rng(998))
    pops = [int(s.body["state"]["pops"]) for s in snaps]
    late_snaps = [s for s, p in zip(snaps, pops) if p > 0]
    assert late_snaps, "dual search produced no post-pop snapshot"
    with pytest.raises(ckpt_mod.CheckpointRejected, match="pop-0"):
        _resume(late_snaps[-1], extra_reads=[late])
    pop0 = [s for s, p in zip(snaps, pops) if p == 0]
    if pop0:  # the first poll may already sit past pop 0
        engine = _resume(pop0[0], extra_reads=[late])
        assert len(engine.sequences) == len(_dual_reads()) + 1
        assert engine.consensus() is not None


def test_priority_rejects_extra_reads():
    _ref, snaps = _cached_snapshots("priority", "python")
    with pytest.raises(ckpt_mod.CheckpointRejected, match="extra_reads"):
        _resume(snaps[0], extra_reads=[b"\x00\x01"])


# ------------------------------------------------- rejection paths


def _one_wire_snapshot():
    """A deep copy — several rejection tests tamper with it in place."""
    _ref, snaps = _cached_snapshots("single", "python")
    return json.loads(json.dumps(snaps[len(snaps) // 2].to_wire()))


def test_version_skew_rejected():
    wire = _one_wire_snapshot()
    wire["version"] = ckpt_mod.CKPT_VERSION + 1
    with pytest.raises(ckpt_mod.CheckpointRejected, match="version"):
        ckpt_mod.SearchCheckpoint.from_wire(wire)


def test_tampered_body_fails_crc():
    wire = _one_wire_snapshot()
    wire["body"]["state"]["pops"] = int(wire["body"]["state"]["pops"]) + 1
    with pytest.raises(ckpt_mod.CheckpointRejected):
        ckpt_mod.SearchCheckpoint.from_wire(wire)


def test_truncated_body_rejected():
    wire = _one_wire_snapshot()
    body = dict(wire["body"])
    del body["state"]
    truncated = ckpt_mod.SearchCheckpoint("single", body).to_wire()
    with pytest.raises(ckpt_mod.CheckpointRejected, match="malformed"):
        ckpt_mod.resume_engine(
            ckpt_mod.SearchCheckpoint.from_wire(truncated)
        )


def test_wrong_engine_kind_rejected():
    wire = _one_wire_snapshot()
    with pytest.raises(ckpt_mod.CheckpointRejected, match="cannot resume"):
        DualConsensusDWFA.resume(wire)


def test_corrupted_read_rejected_by_priority_check():
    """Read corruption that survives the CRC (payload re-signed by an
    attacker or corrupted pre-encode) still cannot poison the search:
    the rebuilt nodes' priorities disagree with the stored ones and the
    restore rejects at consume time.  (Every base is rotated — a lone
    bit-flip past the searched frontier is invisible by design, the
    restored prefix genuinely doesn't depend on it.)"""
    wire = _one_wire_snapshot()
    body = json.loads(json.dumps(wire["body"]))
    read0 = bytes(ckpt_mod.unb64(body["reads"][0]))
    body["reads"][0] = ckpt_mod.b64(bytes((b + 1) % 4 for b in read0))
    resigned = ckpt_mod.SearchCheckpoint("single", body).to_wire()
    engine = ckpt_mod.resume_engine(
        ckpt_mod.SearchCheckpoint.from_wire(resigned)
    )
    with pytest.raises(ckpt_mod.CheckpointRejected, match="priority"):
        engine.consensus()


def test_non_dict_payload_rejected():
    for garbage in (None, 17, "{}", [1, 2], {"version": 1}):
        with pytest.raises(ckpt_mod.CheckpointRejected):
            ckpt_mod.SearchCheckpoint.from_wire(garbage)


# ------------------------------------------------- serving integration


def _serve_request():
    from waffle_con_tpu.serve.job import JobRequest

    return JobRequest(
        kind="single", reads=tuple(_single_reads()),
        config=_cfg("python"),
    )


def test_service_resumes_from_checkpoint():
    from waffle_con_tpu.serve.service import ConsensusService, ServeConfig

    ref, snaps = _cached_snapshots("single", "python")
    wire = json.loads(json.dumps(snaps[len(snaps) // 2].to_wire()))
    svc = ConsensusService(
        ServeConfig(workers=1, name="ckpt-test"), publish_stats=False
    )
    try:
        handle = svc.submit(_serve_request(), checkpoint=wire)
        assert handle.result(timeout=120) == ref
        stats = svc.stats()["checkpoints"]
        assert stats["resumed"] == 1
        assert stats["rejected"] == 0
    finally:
        svc.close()


def test_service_degrades_rejected_checkpoint():
    """A checkpoint whose deferred (consume-time) validation fails must
    restart the search from scratch — job DONE with the right bytes,
    one rejected count, zero resumed — never a failed job."""
    from waffle_con_tpu.serve.service import ConsensusService, ServeConfig

    ref, snaps = _cached_snapshots("single", "python")
    wire = json.loads(json.dumps(snaps[len(snaps) // 2].to_wire()))
    body = json.loads(json.dumps(wire["body"]))
    read0 = bytes(ckpt_mod.unb64(body["reads"][0]))
    body["reads"][0] = ckpt_mod.b64(bytes((b + 1) % 4 for b in read0))
    poisoned = ckpt_mod.SearchCheckpoint("single", body).to_wire()
    svc = ConsensusService(
        ServeConfig(workers=1, name="ckpt-test"), publish_stats=False
    )
    try:
        handle = svc.submit(_serve_request(), checkpoint=poisoned)
        assert handle.result(timeout=120) == ref
        stats = svc.stats()["checkpoints"]
        assert stats["rejected"] == 1
        assert stats["resumed"] == 0
        # the stale resume point must not ride into a re-dispatch
        assert handle.checkpoint is None or handle.checkpoint != poisoned
    finally:
        svc.close()


def test_expired_job_carries_final_checkpoint():
    """Deadline persistence: an EXPIRED job's handle holds the final
    snapshot, and resuming it (fresh budget) finishes byte-identical
    to the uninterrupted search."""
    from waffle_con_tpu.runtime.watchdog import DeadlineExceeded
    from waffle_con_tpu.serve.job import JobRequest, JobStatus
    from waffle_con_tpu.serve.service import ConsensusService, ServeConfig

    ref = _cached_snapshots("single", "python")[0]
    svc = ConsensusService(
        ServeConfig(workers=1, name="ckpt-test"), publish_stats=False
    )
    try:
        handle = svc.submit(JobRequest(
            kind="single", reads=tuple(_single_reads()),
            config=_cfg("python"), deadline_s=0.001,
        ))
        with pytest.raises(DeadlineExceeded):
            handle.result(timeout=120)
        assert handle.status is JobStatus.EXPIRED
        if handle.checkpoint is None:
            pytest.skip("deadline lapsed before the first pop boundary")
        engine = ckpt_mod.resume_engine(
            ckpt_mod.SearchCheckpoint.from_wire(handle.checkpoint)
        )
        assert engine.consensus() == ref
    finally:
        svc.close()
