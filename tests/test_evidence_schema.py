"""Bench evidence schema + perfdb contracts.

Three contracts pinned here:

* the evidence lines ``bench.py`` prints are schema-versioned
  (``perfdb.EVIDENCE_SCHEMA``) and :func:`perfdb.load_evidence`
  validates per-mode required fields and rejects unknown majors;
* every evidence field ``scripts/ci.sh`` hard-indexes
  (``evidence["..."]``) is declared in the
  :data:`perfdb.EVIDENCE_MODE_FIELDS` contract table — so ci.sh
  growing a new assert without updating the table fails tier-1, not
  the next CI run;
* the perfdb JSONL round-trips: schema-stamped append, torn-line and
  future-major tolerance on load, rolling-baseline median math.
"""

import json
import os
import re

import pytest

from waffle_con_tpu.obs import perfdb

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CI_SH = os.path.join(_ROOT, "scripts", "ci.sh")


# -------------------------------------------------- ci.sh field contract


def test_ci_sh_reads_only_declared_evidence_fields():
    with open(_CI_SH) as fh:
        src = fh.read()
    read_fields = set(re.findall(r"""evidence\[["'](\w+)["']\]""", src))
    assert read_fields, "ci.sh no longer hard-indexes evidence fields?"
    declared = set(perfdb.EVIDENCE_REQUIRED)
    for fields in perfdb.EVIDENCE_MODE_FIELDS.values():
        declared.update(fields)
    # trace-enabled smoke extras: present because ci.sh runs the bench
    # with --trace-out / WAFFLE_METRICS, not mode-required fields
    declared.update({"metrics", "search_report"})
    undeclared = read_fields - declared
    assert not undeclared, (
        f"ci.sh reads evidence fields {sorted(undeclared)} that "
        f"perfdb.EVIDENCE_MODE_FIELDS does not declare — update the "
        f"contract table (and load_evidence validation) first"
    )


def test_best_fallback_literal_matches_evidence_schema():
    # bench._BEST is flushed from signal context, so it carries the
    # schema as a literal instead of calling stamp_evidence; pin the
    # literal to the constant so a bump can't silently miss it
    import bench

    assert bench._BEST["schema"] == perfdb.EVIDENCE_SCHEMA


# --------------------------------------------------- evidence validation


def _microbench_line(**overrides):
    line = {
        "metric": "hotloop_steps_per_s",
        "value": 1048.1,
        "unit": "steps/s",
        "mode": "microbench",
        "parity": True,
        "steps": 9983,
        "stop_code": 2,
        "breakdown": {"run_cols": 4},
        "schema": perfdb.EVIDENCE_SCHEMA,
    }
    line.update(overrides)
    return line


def test_load_evidence_accepts_current_schema():
    out = perfdb.load_evidence(json.dumps(_microbench_line()))
    assert out["value"] == 1048.1


def test_load_evidence_missing_required_field():
    bad = _microbench_line()
    del bad["unit"]
    with pytest.raises(ValueError, match="unit"):
        perfdb.load_evidence(bad)


def test_load_evidence_missing_mode_field():
    bad = _microbench_line()
    del bad["stop_code"]
    with pytest.raises(ValueError, match="stop_code"):
        perfdb.load_evidence(bad)


def test_load_evidence_rejects_newer_major():
    with pytest.raises(ValueError, match="newer"):
        perfdb.load_evidence(_microbench_line(schema=99))


def test_load_evidence_rejects_nonsense_major():
    with pytest.raises(ValueError, match="nonsense"):
        perfdb.load_evidence(_microbench_line(schema=0))


def test_load_evidence_missing_schema_is_legacy_major_one():
    # pre-observatory line: no schema field, none of the newer-major
    # guarantees — parses without field checks
    legacy = {"metric": "x", "value": 1}
    assert perfdb.load_evidence(json.dumps(legacy))["metric"] == "x"


def test_load_evidence_rejects_non_object():
    with pytest.raises(ValueError):
        perfdb.load_evidence("[1, 2]")


def test_stamp_evidence_sets_schema():
    out = perfdb.stamp_evidence({"metric": "m"})
    assert out["schema"] == perfdb.EVIDENCE_SCHEMA


def test_every_mode_contract_includes_required_fields_disjointly():
    # the mode tables list only mode-SPECIFIC fields; the cross-mode
    # invariants live in EVIDENCE_REQUIRED alone
    for mode, fields in perfdb.EVIDENCE_MODE_FIELDS.items():
        overlap = set(fields) & set(perfdb.EVIDENCE_REQUIRED)
        assert not overlap, (mode, overlap)


# --------------------------------------------------------- perfdb jsonl


def test_perfdb_round_trip(tmp_path):
    db = tmp_path / "perf.jsonl"
    rec = perfdb.make_record(
        "microbench", "hotloop_steps_per_s", 1048.1, "steps/s",
        platform="cpu", run_cols=4,
    )
    assert rec["schema"] == perfdb.SCHEMA
    assert rec["unix_time"] > 0 and rec["host"]
    path = perfdb.append_record(rec, str(db))
    assert path == str(db)
    loaded = perfdb.load_records(str(db))
    assert len(loaded) == 1
    assert loaded[0]["value"] == 1048.1
    assert loaded[0]["run_cols"] == 4


def test_perfdb_append_refuses_wrong_schema(tmp_path):
    with pytest.raises(ValueError, match="refusing"):
        perfdb.append_record({"schema": 99, "value": 1},
                             str(tmp_path / "x.jsonl"))


def test_perfdb_load_skips_torn_and_future_lines(tmp_path):
    db = tmp_path / "perf.jsonl"
    good = perfdb.make_record("microbench", "m", 10.0, "steps/s")
    with open(db, "w") as fh:
        fh.write(json.dumps(good) + "\n")
        fh.write('{"schema": 1, "kind": "microbench", "val')  # torn
        fh.write("\n")
        fh.write(json.dumps({**good, "schema": perfdb.SCHEMA + 1,
                             "value": 999.0}) + "\n")
        fh.write("[1,2,3]\n")  # not an object
        fh.write(json.dumps({**good, "value": 20.0}) + "\n")
    loaded = perfdb.load_records(str(db))
    assert [r["value"] for r in loaded] == [10.0, 20.0]


def test_perfdb_load_missing_file_is_empty(tmp_path):
    assert perfdb.load_records(str(tmp_path / "nope.jsonl")) == []


def test_perfdb_kind_filter(tmp_path):
    db = str(tmp_path / "perf.jsonl")
    perfdb.append_record(
        perfdb.make_record("microbench", "m", 1.0, "u"), db)
    perfdb.append_record(
        perfdb.make_record("serve", "s", 2.0, "u"), db)
    assert [r["kind"] for r in perfdb.load_records(db, kind="serve")] \
        == ["serve"]


def test_perfdb_default_path_env_override(monkeypatch, tmp_path):
    monkeypatch.setenv("WAFFLE_PERFDB", str(tmp_path / "db.jsonl"))
    assert perfdb.default_path() == str(tmp_path / "db.jsonl")
    monkeypatch.delenv("WAFFLE_PERFDB")
    assert perfdb.default_path().endswith(
        os.path.join("evidence", "perfdb.jsonl"))


def test_rolling_baseline_median_math():
    recs = [{"value": v, "metric": "m"} for v in (10, 30, 20)]
    assert perfdb.rolling_baseline(recs) == 20  # odd: middle
    recs.append({"value": 40, "metric": "m"})
    assert perfdb.rolling_baseline(recs) == 25  # even: mean of middles
    # window keeps only the tail
    assert perfdb.rolling_baseline(recs, window=2) == 30  # of (20, 40)
    # metric filter + non-numeric tolerance
    recs.append({"value": "bogus", "metric": "m"})
    recs.append({"value": 1000, "metric": "other"})
    assert perfdb.rolling_baseline(recs, metric="m") == 25
    assert perfdb.rolling_baseline([], metric="m") is None
