"""Parity tests for the native (C++) scorer and engine against the
Python oracle."""

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
    ConsensusCost,
)
from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.native import (
    NativeScorer,
    native_consensus,
    native_wfa_ed,
)
from waffle_con_tpu.ops.alignment import wfa_ed_config
from waffle_con_tpu.ops.scorer import PythonScorer
from waffle_con_tpu.utils.example_gen import generate_test
from waffle_con_tpu.utils.fixtures import load_dual_fixture


def test_native_wfa_ed_parity():
    rng = np.random.default_rng(21)
    for _ in range(30):
        a = bytes(rng.integers(0, 4, size=rng.integers(0, 40)))
        b = bytes(rng.integers(0, 4, size=rng.integers(0, 40)))
        for both in (True, False):
            assert native_wfa_ed(a, b, both, None) == wfa_ed_config(
                a, b, both, None
            )


def test_native_scorer_walk_parity():
    rng = np.random.default_rng(22)
    reads = [bytes(rng.integers(0, 4, size=rng.integers(10, 40))) for _ in range(6)]
    config = CdwfaConfig()
    py = PythonScorer(reads, config)
    nt = NativeScorer(reads, config)
    hp = py.root(np.ones(6, dtype=bool))
    hn = nt.root(np.ones(6, dtype=bool))
    consensus = b""
    for step in range(30):
        sp = py.stats(hp, consensus)
        if step % 5 == 4:
            sym = int(rng.integers(0, 4))
        else:
            sym = int(py.symtab[int(np.argmax(sp.occ.sum(axis=0)))])
        consensus += bytes([sym])
        a = py.push(hp, consensus)
        b = nt.push(hn, consensus)
        np.testing.assert_array_equal(a.eds, b.eds)
        np.testing.assert_array_equal(a.occ, b.occ)
        np.testing.assert_array_equal(a.split, b.split)
        np.testing.assert_array_equal(a.reached, b.reached)
    np.testing.assert_array_equal(
        py.finalized_eds(hp, consensus), nt.finalized_eds(hn, consensus)
    )


def test_native_backend_single_engine():
    truth, reads = generate_test(4, 60, 8, 0.02, seed=17)
    results = {}
    for backend in ("python", "native"):
        engine = ConsensusDWFA(CdwfaConfigBuilder().backend(backend).build())
        for r in reads:
            engine.add_sequence(r)
        results[backend] = engine.consensus()
    assert results["python"] == results["native"]


def test_native_backend_dual_engine():
    sequences, expected = load_dual_fixture(
        "dual_001", True, ConsensusCost.L1_DISTANCE
    )
    engine = DualConsensusDWFA(
        CdwfaConfigBuilder().wildcard(ord("*")).backend("native").build()
    )
    for s in sequences:
        engine.add_sequence(s)
    assert engine.consensus() == [expected]


def test_native_full_engine_parity():
    # the complete C++ engine against the Python engine, including scores
    truth, reads = generate_test(4, 80, 10, 0.02, seed=33)
    engine = ConsensusDWFA()
    for r in reads:
        engine.add_sequence(r)
    expected = engine.consensus()
    got = native_consensus(reads)
    assert [(c.sequence, c.scores) for c in expected] == got


def test_native_full_engine_wildcards_and_l2():
    sequences = [b"ACGTACCGT****", b"**GTATGTAC**", b"****ACGTACGT"]
    for cost in (ConsensusCost.L1_DISTANCE, ConsensusCost.L2_DISTANCE):
        cfg = (
            CdwfaConfigBuilder()
            .wildcard(ord("*"))
            .consensus_cost(cost)
            .build()
        )
        engine = ConsensusDWFA(cfg)
        for s in sequences:
            engine.add_sequence(s)
        expected = engine.consensus()
        got = native_consensus(sequences, config=cfg)
        assert [(c.sequence, c.scores) for c in expected] == got


def test_native_full_engine_offsets():
    sequences = [b"ACGTACGTACGTACGT", b"ACGTACGTACGT", b"GTACGTACGT"]
    offsets = [None, 4, 7]
    cfg = CdwfaConfigBuilder().offset_window(1).offset_compare_length(4).build()
    engine = ConsensusDWFA(cfg)
    for s, o in zip(sequences, offsets):
        engine.add_sequence_offset(s, o)
    expected = engine.consensus()
    got = native_consensus(sequences, offsets, cfg)
    assert [(c.sequence, c.scores) for c in expected] == got
