"""Out-of-process serving: wire codec hardening, front-door routing /
health / crash-requeue semantics (fake in-thread workers speaking the
real protocol), and real worker-process parity + SIGKILL drills.

The fake-worker tests exercise every door-side path without spawning
an interpreter per worker: ``ProcConfig.launcher`` is the seam — a
thread connects to the door's socket and speaks byte-identical frames,
fabricating results instead of running engines.  The two subprocess
tests (marked ``slow``; the CI smoke drives the same paths through
``bench.py --storm --procs``) prove the real
``python -m waffle_con_tpu.serve.procs.worker`` stack end to end.
"""

import os
import signal
import socket
import threading
import time

import pytest

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.models.consensus import Consensus
from waffle_con_tpu.models.dual_consensus import DualConsensus
from waffle_con_tpu.models.priority_consensus import PriorityConsensus
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.runtime.liveness import Heartbeats, WorkerLost
from waffle_con_tpu.serve import (
    JobRequest,
    JobStatus,
    ProcConfig,
    ProcFrontDoor,
    ServiceOverloaded,
)
from waffle_con_tpu.serve.procs import wire

pytestmark = pytest.mark.serve


# ---------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------

def test_frame_roundtrip_every_type():
    decoder = wire.FrameDecoder()
    payloads = {ftype: {"n": int(ftype), "name": ftype.name}
                for ftype in wire.FrameType}
    blob = b"".join(
        wire.encode_frame(ftype, obj) for ftype, obj in payloads.items()
    )
    frames = decoder.feed(blob)
    assert [(f, o) for f, o in frames] == list(payloads.items())
    assert decoder.pending() == 0


def test_torn_frames_buffer_without_hanging():
    # one byte at a time: nothing decodes until the frame completes,
    # and the decoder never blocks or raises on partial input
    frame = wire.encode_frame(wire.FrameType.PING, {"x": 1})
    decoder = wire.FrameDecoder()
    for byte in frame[:-1]:
        assert decoder.feed(bytes([byte])) == []
    assert decoder.feed(frame[-1:]) == [(wire.FrameType.PING, {"x": 1})]


def test_two_frames_in_one_chunk_plus_tail():
    a = wire.encode_frame(wire.FrameType.PING, {})
    b = wire.encode_frame(wire.FrameType.PONG, {"outstanding": 2})
    c = wire.encode_frame(wire.FrameType.DRAIN, {})
    decoder = wire.FrameDecoder()
    got = decoder.feed(a + b + c[:4])
    assert [f for f, _ in got] == [wire.FrameType.PING, wire.FrameType.PONG]
    assert decoder.feed(c[4:]) == [(wire.FrameType.DRAIN, {})]


def test_bad_checksum_is_typed():
    frame = bytearray(wire.encode_frame(wire.FrameType.RESULT, {"job": 1}))
    frame[-1] ^= 0xFF  # flip a payload byte; header CRC now mismatches
    with pytest.raises(wire.BadChecksum):
        wire.FrameDecoder().feed(bytes(frame))


def test_future_version_is_typed():
    frame = bytearray(wire.encode_frame(wire.FrameType.PING, {}))
    frame[0] = wire.FRAME_VERSION + 1
    with pytest.raises(wire.UnsupportedVersion):
        wire.FrameDecoder().feed(bytes(frame))


def test_unknown_frame_type_is_typed():
    payload = b"{}"
    import zlib

    frame = wire.HEADER.pack(
        wire.FRAME_VERSION, 200, len(payload), zlib.crc32(payload)
    ) + payload
    with pytest.raises(wire.UnknownFrameType):
        wire.FrameDecoder().feed(frame)


def test_oversized_declared_length_is_typed(monkeypatch):
    monkeypatch.setenv("WAFFLE_PROC_FRAME_MAX", "4096")
    header = wire.HEADER.pack(wire.FRAME_VERSION, 1, 1 << 20, 0)
    with pytest.raises(wire.FrameTooLarge):
        wire.FrameDecoder().feed(header)
    with pytest.raises(wire.FrameTooLarge):
        wire.encode_frame(wire.FrameType.SUBMIT, {"x": "a" * 8192})


def test_garbage_payload_is_typed_never_a_hang():
    # correct header + CRC over non-JSON bytes: typed WireError
    import zlib

    payload = b"\xff\xfe not json"
    frame = wire.HEADER.pack(
        wire.FRAME_VERSION, int(wire.FrameType.PING), len(payload),
        zlib.crc32(payload),
    ) + payload
    with pytest.raises(wire.WireError):
        wire.FrameDecoder().feed(frame)


def test_header_fuzz_never_untyped(monkeypatch):
    # every mutation of a valid frame must raise a WireError subclass
    # or decode cleanly — nothing untyped, nothing hangs
    monkeypatch.setenv("WAFFLE_PROC_FRAME_MAX", "65536")
    base = wire.encode_frame(wire.FrameType.HEALTH, {"reason": "x"})
    import random

    rng = random.Random(20260806)
    for _ in range(300):
        blob = bytearray(base)
        for _ in range(rng.randint(1, 4)):
            blob[rng.randrange(len(blob))] = rng.randrange(256)
        decoder = wire.FrameDecoder()
        try:
            decoder.feed(bytes(blob))
        except wire.WireError:
            pass


def test_config_codec_roundtrip():
    cfg = CdwfaConfig(
        consensus_cost=ConsensusCost.L2_DISTANCE, max_queue_size=7,
        min_af=0.25, wildcard=ord("N"), backend="jax", mesh_shards=2,
        initial_band=32, backend_chain=("jax", "python"),
        supervised=True, dual_max_ed_delta=9,
    )
    assert wire.decode_config(wire.encode_config(cfg)) == cfg
    assert wire.decode_config(None) is None
    # unknown fields from a newer peer are dropped, not fatal
    obj = wire.encode_config(cfg)
    obj["knob_from_the_future"] = 42
    assert wire.decode_config(obj) == cfg


def test_request_codec_roundtrip_all_kinds():
    single = JobRequest(kind="single", reads=(b"ACGT", b"ACG"),
                        offsets=(None, 1), priority=2, deadline_s=9.0,
                        tag="t", config=CdwfaConfig())
    rt = wire.decode_request(wire.encode_request(single))
    assert (rt.kind, rt.reads, rt.offsets, rt.priority, rt.tag) == \
        (single.kind, single.reads, single.offsets, single.priority,
         single.tag)
    assert rt.config == single.config
    chain = JobRequest(kind="priority",
                       reads=((b"AC", b"ACGT"), (b"AG", b"ACGA")))
    assert wire.decode_request(wire.encode_request(chain)).reads == \
        chain.reads
    # the door rewrites the deadline to the REMAINING budget
    sent = wire.encode_request(single, deadline_left_s=1.5)
    assert sent["deadline_s"] == 1.5


def test_result_codec_roundtrip_all_kinds():
    c1 = Consensus(b"ACGT", ConsensusCost.L1_DISTANCE, [0, 1])
    c2 = Consensus(b"ACGA", ConsensusCost.L1_DISTANCE, [2, 0])
    single = [c1, c2]
    assert wire.decode_result(
        "single", wire.encode_result("single", single)
    ) == single
    dual = [DualConsensus(c1, c2, [True, False], [0, None], [None, 0]),
            DualConsensus(c1, None, [True, True], [0, 1], [None, None])]
    assert wire.decode_result(
        "dual", wire.encode_result("dual", dual)
    ) == dual
    prio = PriorityConsensus([[c1], [c1, c2]], [0, 1])
    assert wire.decode_result(
        "priority", wire.encode_result("priority", prio)
    ) == prio
    with pytest.raises(wire.WireError):
        wire.encode_result("nope", [])
    with pytest.raises(wire.WireError):
        wire.decode_result("single", [{"bad": 1}])


# ---------------------------------------------------------------------
# fake in-thread workers: full protocol, scripted behaviour
# ---------------------------------------------------------------------

class FakeWorker:
    """A worker that is really a thread: connects to the door's
    socket, HELLOs, and answers SUBMITs with fabricated results.

    ``behavior`` per worker name:
      * ``"ok"`` — STARTED then RESULT for every job;
      * ``"crash-after-start"`` — STARTED for the first job, then the
        socket slams shut (simulates SIGKILL mid-job);
      * ``"silent"`` — HELLO then never answers anything (liveness
        lapse path);
      * ``"hold"`` — accepts jobs, never finishes them (drain tests);
      * ``"demote_hold"`` — first job: forward a backend_demoted
        HEALTH trigger, STARTED, then hold the result until
        ``release`` is set (drain-then-readmit tests).
    """

    def __init__(self, socket_path, name, spec, behavior="ok",
                 triggers=None):
        self.name = name
        self.behavior = behavior
        self.triggers = list(triggers or [])
        self.jobs_seen = []
        self.release = threading.Event()
        self.pid = os.getpid()
        self._exited = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(socket_path,), daemon=True
        )
        self._thread.start()

    # Popen-like surface the door's watchdog expects
    def poll(self):
        return None if not self._exited.is_set() else 0

    def wait(self, timeout=None):
        self._exited.wait(timeout)
        return 0

    def terminate(self):
        self._exited.set()

    kill = terminate

    def _reply(self, sock, job_id, request):
        result = [Consensus(
            b"FAKE", ConsensusCost.L1_DISTANCE, [0] * len(request.reads)
        )]
        sock.sendall(wire.encode_frame(
            wire.FrameType.STARTED, {"job": job_id}
        ))
        sock.sendall(wire.encode_frame(wire.FrameType.RESULT, {
            "job": job_id, "kind": "single",
            "result": wire.encode_result("single", result),
        }))

    def _run(self, socket_path):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(socket_path)
        decoder = wire.FrameDecoder()
        sock.sendall(wire.encode_frame(wire.FrameType.HELLO, {
            "worker": self.name, "pid": self.pid, "slots": 2,
        }))
        for trig in self.triggers:
            sock.sendall(wire.encode_frame(wire.FrameType.HEALTH, trig))
        try:
            while True:
                data = sock.recv(65536)
                if not data:
                    return
                if self.behavior == "silent":
                    continue
                for ftype, obj in decoder.feed(data):
                    if ftype is wire.FrameType.PING:
                        sock.sendall(wire.encode_frame(
                            wire.FrameType.PONG,
                            {"outstanding": 0, "slots": 2},
                        ))
                    elif ftype is wire.FrameType.SUBMIT:
                        job_id = obj["job"]
                        request = wire.decode_request(obj["request"])
                        self.jobs_seen.append(job_id)
                        if self.behavior == "hold":
                            continue
                        if self.behavior == "crash-after-start":
                            sock.sendall(wire.encode_frame(
                                wire.FrameType.STARTED, {"job": job_id}
                            ))
                            return  # slam the socket mid-job
                        if (self.behavior == "demote_hold"
                                and len(self.jobs_seen) == 1):
                            sock.sendall(wire.encode_frame(
                                wire.FrameType.HEALTH,
                                {"worker": self.name,
                                 "reason": "backend_demoted",
                                 "trace": f"{self.name}/job-{job_id}",
                                 "detail": {}},
                            ))
                            sock.sendall(wire.encode_frame(
                                wire.FrameType.STARTED, {"job": job_id}
                            ))

                            def _later(jid=job_id, req=request):
                                self.release.wait(10)
                                try:
                                    sock.sendall(wire.encode_frame(
                                        wire.FrameType.RESULT, {
                                            "job": jid, "kind": "single",
                                            "result": wire.encode_result(
                                                "single",
                                                [Consensus(
                                                    b"FAKE",
                                                    ConsensusCost.L1_DISTANCE,
                                                    [0] * len(req.reads),
                                                )],
                                            ),
                                        }
                                    ))
                                except OSError:
                                    pass

                            threading.Thread(
                                target=_later, daemon=True
                            ).start()
                            continue
                        self._reply(sock, job_id, obj and request)
                    elif ftype is wire.FrameType.SHUTDOWN:
                        return
        except OSError:
            pass
        finally:
            self._exited.set()
            try:
                sock.close()
            except OSError:
                pass


class FakeFleet:
    """Launcher seam: hands the door FakeWorkers by scripted name."""

    def __init__(self, behaviors=None, triggers=None):
        self.behaviors = behaviors or {}
        self.triggers = triggers or {}
        self.workers = {}

    def __call__(self, socket_path, name, spec):
        worker = FakeWorker(
            socket_path, name, spec,
            behavior=self.behaviors.get(name, "ok"),
            triggers=self.triggers.get(name),
        )
        self.workers[name] = worker
        return worker


def _request(n_reads=2):
    return JobRequest(kind="single", reads=(b"ACGT",) * n_reads,
                      config=CdwfaConfig())


def _door(fleet, **kw):
    kw.setdefault("workers", 2)
    kw.setdefault("name", "fake")
    kw.setdefault("spawn_timeout_s", 10.0)
    return ProcFrontDoor(ProcConfig(launcher=fleet, **kw))


def test_fake_fleet_routes_and_decodes():
    fleet = FakeFleet()
    with _door(fleet) as door:
        handles = [door.submit(_request()) for _ in range(6)]
        results = [h.result(timeout=10) for h in handles]
    assert all(r[0].sequence == b"FAKE" for r in results)
    stats = door.worker_stats()
    assert sum(w["routed"] for w in stats) == 6
    assert all(w["routed"] > 0 for w in stats)  # both participated


def test_health_demotion_drains_then_readmits():
    # the routing tie-break is worker index: the first job lands on w0
    fleet = FakeFleet(behaviors={"fake:w0": "demote_hold"})
    with _door(fleet) as door:
        first = door.submit(_request())
        # the first job's worker demotes itself and holds the result:
        # it must show DRAINING while the job is still outstanding
        deadline = time.monotonic() + 5
        demoted = None
        while time.monotonic() < deadline and demoted is None:
            demoted = next(
                (w for w in door.worker_stats()
                 if w["state"] == "draining"), None,
            ) or time.sleep(0.01)
        assert demoted, door.worker_stats()
        healthy = next(w["worker"] for w in door.worker_stats()
                       if w["worker"] != demoted["worker"])
        # while draining with a healthy peer, nothing new routes to it
        for _ in range(4):
            door.submit(_request()).result(timeout=10)
        stats = {w["worker"]: w for w in door.worker_stats()}
        assert stats[healthy]["routed"] == 4
        assert stats[demoted["worker"]]["routed"] == 1
        assert stats[demoted["worker"]]["demotions"] == 1
        # release the held job: drained (zero outstanding) means the
        # next routing decision re-admits it
        fleet.workers[demoted["worker"]].release.set()
        assert first.result(timeout=10)[0].sequence == b"FAKE"
        door.submit(_request()).result(timeout=10)
        stats = {w["worker"]: w for w in door.worker_stats()}
        assert stats[demoted["worker"]]["state"] == "up"
        assert stats[demoted["worker"]]["readmits"] == 1


def test_health_slow_search_sheds_with_cooldown():
    fleet = FakeFleet(triggers={
        "fake:w1": [{"worker": "fake:w1", "reason": "slow_search",
                     "trace": "fake:w1/job-9", "detail": {}}],
    })
    with _door(fleet, shed_cooldown_s=0.2) as door:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            states = {w["worker"]: w["state"]
                      for w in door.worker_stats()}
            if states["fake:w1"] == "shedding":
                break
            time.sleep(0.01)
        assert states["fake:w1"] == "shedding"
        time.sleep(0.25)  # cooldown expires at the next routing pass
        door.submit(_request()).result(timeout=10)
        states = {w["worker"]: w["state"] for w in door.worker_stats()}
        assert states["fake:w1"] == "up"
        sheds = {w["worker"]: w["sheds"] for w in door.worker_stats()}
        assert sheds["fake:w1"] == 1


def test_health_unknown_reason_counted_not_silent():
    # forward-compat backstop: a HEALTH reason this door doesn't know
    # must not change routing state, but it must be visible — a
    # runtime event plus a reason-labeled counter, never a silent drop
    from waffle_con_tpu.obs import metrics as obs_metrics
    from waffle_con_tpu.runtime import events as runtime_events

    obs_metrics.enable_metrics(True)
    obs_metrics.registry().reset()
    try:
        fleet = FakeFleet(triggers={
            "fake:w0": [{"worker": "fake:w0",
                         "reason": "reason_from_the_future",
                         "trace": "fake:w0/job-1", "detail": {}}],
        })
        with _door(fleet) as door:
            deadline = time.monotonic() + 5
            ignored = []
            while time.monotonic() < deadline and not ignored:
                ignored = runtime_events.get_events("door_health_ignored")
                time.sleep(0.01)
            assert ignored, "ignored-HEALTH event never recorded"
            assert ignored[-1]["worker"] == "fake:w0"
            assert ignored[-1]["reason"] == "reason_from_the_future"
            # routing state untouched: the worker is still UP and takes
            # jobs
            door.submit(_request()).result(timeout=10)
            states = {w["worker"]: w["state"]
                      for w in door.worker_stats()}
            assert states["fake:w0"] == "up"
        text = obs_metrics.registry().render_prometheus()
        assert "waffle_door_health_ignored_total" in text
        assert 'reason="reason_from_the_future"' in text
    finally:
        obs_metrics.registry().reset()
        obs_metrics.reset_metrics_enabled()


def test_crashed_worker_requeues_and_single_incident():
    obs_flight.reset()
    fleet = FakeFleet(behaviors={"fake:w0": "crash-after-start"})
    with _door(fleet, worker_slots=1, inflight=1) as door:
        handles = [door.submit(_request()) for _ in range(4)]
        results = [h.result(timeout=10) for h in handles]
        assert all(r[0].sequence == b"FAKE" for r in results)
        stats = {w["worker"]: w for w in door.worker_stats()}
        assert stats["fake:w0"]["state"] == "lost"
        # the started job restarted + any queued job requeued
        assert stats["fake:w0"]["requeues"] >= 1
        assert stats["fake:w1"]["routed"] == 4
    incidents = [i for i in obs_flight.incidents()
                 if i["reason"] == "worker_lost"]
    assert len(incidents) == 1  # exactly one, despite reader+watchdog


def test_restart_lost_off_fails_started_jobs_typed():
    obs_flight.reset()
    fleet = FakeFleet(behaviors={"fake:w0": "crash-after-start",
                                 "fake:w1": "crash-after-start"})
    with _door(fleet, restart_lost=False, worker_slots=1,
               inflight=1) as door:
        handle = door.submit(_request())
        assert handle.wait(10)
        assert handle.status is JobStatus.FAILED
        with pytest.raises(WorkerLost):
            handle.result(timeout=0)


def test_silent_worker_hits_liveness_lapse(monkeypatch):
    monkeypatch.setenv("WAFFLE_PROC_PING_S", "0.05")
    monkeypatch.setenv("WAFFLE_PROC_LIVENESS_S", "0.3")
    obs_flight.reset()
    fleet = FakeFleet(behaviors={"fake:w0": "silent"})
    with _door(fleet, worker_slots=1, inflight=1) as door:
        handles = [door.submit(_request()) for _ in range(3)]
        results = [h.result(timeout=10) for h in handles]
        assert all(r[0].sequence == b"FAKE" for r in results)
        states = {w["worker"]: w["state"] for w in door.worker_stats()}
        assert states["fake:w0"] == "lost"


def test_admission_rejects_when_full():
    fleet = FakeFleet(behaviors={"fake:w0": "hold"})
    door = _door(fleet, workers=1, queue_limit=2, worker_slots=1,
                 inflight=1)
    try:
        # the held worker absorbs the routing window; the bounded
        # queue behind it fills and the door rejects, never blocks
        with pytest.raises(ServiceOverloaded):
            for _ in range(12):
                door.submit(_request())
    finally:
        door.close(cancel_pending=True, timeout=2.0)


def test_oversized_submit_fails_job_not_router(monkeypatch):
    # a request whose SUBMIT frame exceeds WAFFLE_PROC_FRAME_MAX must
    # fail that one job; the (singleton) router thread keeps routing
    monkeypatch.setenv("WAFFLE_PROC_FRAME_MAX", "4096")
    fleet = FakeFleet()
    with _door(fleet) as door:
        big = JobRequest(kind="single",
                         reads=(b"A" * 8192, b"A" * 8192),
                         config=CdwfaConfig())
        handle = door.submit(big)
        assert handle.wait(10)
        assert handle.status is JobStatus.FAILED
        with pytest.raises(wire.FrameTooLarge):
            handle.result(timeout=0)
        # nothing stays assigned and later jobs still route + finish
        assert all(w["outstanding"] == 0 for w in door.worker_stats())
        follow_up = door.submit(_request())
        assert follow_up.result(timeout=10)[0].sequence == b"FAKE"


def test_dispatch_send_failure_respects_worker_lost_ownership():
    # the OSError path requeues only when the job is still assigned;
    # when a concurrent _worker_lost already popped + requeued it, a
    # second append would run the job twice
    from waffle_con_tpu.serve.job import JobHandle

    door = ProcFrontDoor(
        ProcConfig(workers=1, launcher=lambda *a: None), autostart=False
    )
    try:
        worker = door._workers[0]
        a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
        a.close()
        b.close()
        worker.sock = a  # every sendall raises OSError
        handle = JobHandle(0, _request(), service="fake")
        worker.assigned[0] = handle
        assert door._dispatch(worker, handle) is False
        assert list(door._retry) == [handle]  # still owned: requeued
        assert 0 not in worker.assigned
        door._retry.clear()
        assert door._dispatch(worker, handle) is False
        assert not door._retry  # already taken by _worker_lost: not ours
    finally:
        door.close(timeout=0.1)


def test_worker_unencodable_result_settles_as_error(monkeypatch):
    # worker side: a DONE job whose result cannot be framed (NaN score
    # under allow_nan=False) must still send ERROR, never go silent
    from waffle_con_tpu.analysis import lockcheck
    from waffle_con_tpu.serve.procs.worker import _Worker as ProcWorker

    side_a, side_b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        worker = ProcWorker.__new__(ProcWorker)
        worker._sock = side_a
        worker._name = "t"
        worker._send_lock = lockcheck.make_lock("test.procs.worker.send")

        class DoneHandle:
            status = JobStatus.DONE
            started_at = 1.0
            request = _request()

            def wait_running(self, timeout=None):
                return True

            def wait(self, timeout=None):
                return True

            def result(self, timeout=None):
                return [Consensus(b"ACGT", ConsensusCost.L1_DISTANCE,
                                  [float("nan")])]

        worker._watch(7, DoneHandle())
        side_b.settimeout(5)
        decoder = wire.FrameDecoder()
        frames = []
        while len(frames) < 2:
            frames.extend(decoder.feed(side_b.recv(65536)))
        kinds = [ftype for ftype, _ in frames]
        assert kinds == [wire.FrameType.STARTED, wire.FrameType.ERROR]
        obj = frames[-1][1]
        assert obj["job"] == 7 and obj["kind"] == "failed"
        assert "wire-encodable" in obj["message"]
    finally:
        side_a.close()
        side_b.close()


def test_handshake_timeout_reaps_spawned_workers():
    # start() raising must not leak the worker processes it launched

    class DeadProc:
        def __init__(self):
            self.terminated = False

        def poll(self):
            return None

        def terminate(self):
            self.terminated = True

        def wait(self, timeout=None):
            if not self.terminated:
                raise RuntimeError("still alive")
            return 0

        def kill(self):
            self.terminated = True

    procs = []

    def launcher(socket_path, name, spec):
        proc = DeadProc()
        procs.append(proc)
        return proc  # never connects: the handshake must time out

    with pytest.raises(RuntimeError, match="handshake timed out"):
        ProcFrontDoor(ProcConfig(workers=2, launcher=launcher,
                                 spawn_timeout_s=0.2))
    assert len(procs) == 2
    assert all(p.terminated for p in procs)


def test_heartbeats_ledger():
    clock = [0.0]
    beats = Heartbeats(clock=lambda: clock[0])
    beats.beat("a")
    clock[0] = 1.0
    beats.beat("b")
    clock[0] = 3.0
    assert beats.age("a") == 3.0
    assert beats.lapsed(2.5) == ["a"]
    assert sorted(beats.lapsed(0.5)) == ["a", "b"]
    beats.forget("a")
    assert beats.age("a") is None


# ---------------------------------------------------------------------
# real worker processes (slow: ~seconds of interpreter+jax spawn each;
# the CI smoke exercises the same stack via bench.py --storm --procs)
# ---------------------------------------------------------------------

def _python_cfg(**kw):
    return CdwfaConfig(backend="python", min_count=2, **kw)


def test_subprocess_worker_end_to_end_parity():
    from waffle_con_tpu.serve.service import _build_engine

    reqs = [
        JobRequest(kind="single", reads=(b"ACGTACGTAC",) * 3,
                   config=_python_cfg()),
        JobRequest(kind="dual",
                   reads=(b"ACGTACGTAC", b"ACGTACGTAC",
                          b"ACTTACGTAC", b"ACTTACGTAC"),
                   config=_python_cfg()),
        JobRequest(kind="priority",
                   reads=((b"ACGT", b"ACGTACGT"),
                          (b"ACGA", b"ACGTACGA"),
                          (b"ACGT", b"ACGTACGT")),
                   config=_python_cfg()),
    ]
    refs = [_build_engine(r).consensus() for r in reqs]
    with ProcFrontDoor(ProcConfig(workers=1, name="e2e")) as door:
        handles = [door.submit(r) for r in reqs for _ in range(2)]
        results = [h.result(timeout=60) for h in handles]
    for i, ref in enumerate(refs):
        assert results[2 * i] == ref
        assert results[2 * i + 1] == ref


@pytest.mark.slow
def test_subprocess_sigkill_drill(monkeypatch):
    monkeypatch.setenv("WAFFLE_PROC_PING_S", "0.2")
    monkeypatch.setenv("WAFFLE_PROC_LIVENESS_S", "2.0")
    obs_flight.reset()
    from waffle_con_tpu.serve.service import _build_engine

    req = JobRequest(
        kind="dual", reads=(b"ACGTACGTACGTACGTACGT" * 3,) * 5,
        config=_python_cfg(),
    )
    ref = _build_engine(req).consensus()
    with ProcFrontDoor(ProcConfig(
        workers=2, worker_slots=1, name="drill",
    )) as door:
        handles = [door.submit(req) for _ in range(8)]
        time.sleep(0.3)
        victim = next(w for w in door.worker_stats() if w["pid"])
        os.kill(victim["pid"], signal.SIGKILL)
        results = [h.result(timeout=120) for h in handles]
    assert all(r == ref for r in results)  # parity survives the crash
    stats = {w["worker"]: w for w in door.worker_stats()}
    assert stats[victim["worker"]]["state"] == "lost"
    assert sum(w["requeues"] for w in stats.values()) >= 1
    incidents = [i for i in obs_flight.incidents()
                 if i["reason"] == "worker_lost"]
    assert len(incidents) == 1
