"""Search audit plane: decision recorder, first-divergence differ, and
lockstep shadow execution (``obs/audit.py``).

Covers the zero-cost-when-disabled contract (the audit decision is made
once at search start; no digest work ever runs when off), the per-pop
decision records each engine emits and their expansion into comparable
units, the ring bound and JSONL stream modes, the order-independent
first-divergence differ, clean lockstep shadow runs over both engines,
a seeded ``flip_vote`` divergence aborting the shadow with exactly one
``parity_divergence`` flight incident, and the
``waffle_audit_records_total`` metrics counter.
"""

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.obs import audit as obs_audit
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.ops.scorer import construct_backend

#: a clean 2-vs-1 fork then an unambiguous tail: branch pops through the
#: fork, device runs down the tail
SINGLE_READS = (
    b"ACGTTGCAACGTTGCA",
    b"ACGTTGCAACGTTGCA",
    b"ACCTTGCAACGTTGCA",
)

DUAL_READS = (
    b"ACGTTGCAACGTTGCA",
    b"ACGTTGCAACGTTGCA",
    b"ACGTAGCAACGTTGCA",
    b"ACGTAGCAACGTTGCA",
)


def _cfg(backend, **kw):
    b = CdwfaConfigBuilder().min_count(kw.pop("min_count", 1)).backend(backend)
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _single(backend, reads=SINGLE_READS, **kw):
    engine = ConsensusDWFA(_cfg(backend, **kw))
    for r in reads:
        engine.add_sequence(r)
    return engine


def _dual(backend, reads=DUAL_READS, **kw):
    engine = DualConsensusDWFA(_cfg(backend, min_count=2, **kw))
    for r in reads:
        engine.add_sequence(r)
    return engine


# ------------------------------------------------- zero-overhead guard


def test_disabled_search_sink_is_none():
    # WAFFLE_AUDIT unset in tier-1 runs -> the one flag read per search
    assert obs_audit.search_sink("single") is None
    assert not obs_audit.audit_enabled()


def test_disabled_maybe_tap_returns_scorer_unchanged():
    scorer = construct_backend(list(SINGLE_READS), _cfg("python"), "python")
    assert obs_audit.maybe_tap(scorer, "python") is scorer


def test_disabled_search_does_no_digest_work(monkeypatch):
    """The zero-overhead contract, deterministically: with audit off the
    engines must never reach any digest helper, so poisoning them all is
    invisible to a search."""

    def _poison(*_a, **_k):  # pragma: no cover - must never run
        raise AssertionError("audit digest work ran with audit disabled")

    for name in ("crc_bytes", "active_digest", "b64", "tail"):
        monkeypatch.setattr(obs_audit, name, _poison)
    results = _single("python").consensus()
    assert results and results[0].sequence


def test_enabled_search_reaches_digests(monkeypatch):
    """Counter-probe for the poison test: with capture installed the
    same search DOES hit the digest helpers."""
    hits = []
    real = obs_audit.crc_bytes
    monkeypatch.setattr(
        obs_audit, "crc_bytes", lambda *a: hits.append(1) or real(*a)
    )
    with obs_audit.capture():
        _single("python").consensus()
    assert hits


# -------------------------------------------------- decision recording


def test_capture_python_single_records():
    with obs_audit.capture() as sinks:
        results = _single("python").consensus()
    assert results
    (sink,) = sinks
    assert sink.engine == "single"
    kinds = {r["kind"] for r in sink.records}
    assert "branch" in kinds and "final" in kinds
    pops = [r["pop"] for r in sink.records if "pop" in r]
    assert pops == sorted(pops)
    seqs = [r["seq"] for r in sink.records]
    assert seqs == list(range(len(seqs)))
    units = []
    for rec in sink.records:
        units.extend(obs_audit.expand_units(rec))
    assert units  # every decision expands into comparable units
    for key, value in units:
        assert key[0] in ("s", "p", "d")


def test_capture_dual_records_have_specs():
    with obs_audit.capture() as sinks:
        _dual("python").consensus()
    (sink,) = sinks
    assert sink.engine == "dual"
    branch = [r for r in sink.records if r["kind"] == "branch"]
    assert branch and all("specs" in r for r in branch)
    final = [r for r in sink.records if r["kind"] == "final"]
    assert final and all("imbalanced" in r for r in final)


def test_jax_run_records_and_dispatch_tap():
    with obs_audit.capture() as sinks:
        _single("jax").consensus()
    (sink,) = sinks
    kinds = {r["kind"] for r in sink.records}
    assert "run" in kinds  # device runs recorded at the pop boundary
    taps = [r for r in sink.records if r["kind"] == "dispatch"]
    assert taps and all(
        r["op"] in obs_audit._TAPPED_OPS and r["backend"] == "jax"
        for r in taps
    )
    runs = [r for r in sink.records if r["kind"] == "run"]
    for rec in runs:
        assert rec["via"] in ("run", "mega")
        assert isinstance(rec["code"], int)


def test_ring_bound():
    sink = obs_audit.AuditSink("single", ring=4)
    for i in range(10):
        sink.emit({"kind": "ignored", "pop": i})
    assert len(sink.records) == 4
    assert [r["pop"] for r in sink.records] == [6, 7, 8, 9]
    assert sink.records[-1]["seq"] == 9  # seq keeps counting past the cap


def test_env_file_mode_streams_jsonl(tmp_path, monkeypatch):
    monkeypatch.setenv("WAFFLE_AUDIT", "1")
    monkeypatch.setenv("WAFFLE_AUDIT_DIR", str(tmp_path))
    monkeypatch.setenv("WAFFLE_AUDIT_RING", "5")
    _single("python").consensus()
    logs = sorted(tmp_path.glob("audit-*-single.jsonl"))
    assert len(logs) == 1
    records = obs_audit.load_log(str(logs[0]))
    assert records and all(r["eng"] == "single" for r in records)
    # the stream keeps everything; the in-memory ring stays bounded
    with obs_audit._RECENT_LOCK:
        sink = obs_audit._RECENT[-1]
    assert len(sink.records) <= 5 <= len(records)


def test_priority_group_markers():
    from waffle_con_tpu.models.priority_consensus import (
        PriorityConsensusDWFA,
    )

    engine = PriorityConsensusDWFA(_cfg("python", min_count=1))
    for r in DUAL_READS:
        engine.add_sequence_chain([r])
    with obs_audit.capture() as sinks:
        engine.consensus()
    pri = [s for s in sinks if s.engine == "priority"]
    assert pri
    groups = [r for r in pri[0].records if r["kind"] == "group"]
    assert groups and all(
        {"level", "include", "size"} <= set(r) for r in groups
    )


# ------------------------------------------------ first-divergence diff


def test_diff_logs_identical_and_cross_backend():
    with obs_audit.capture(strict_align=True) as sinks:
        _single("python").consensus()
        _single("jax").consensus()
    py, jx = sinks
    assert obs_audit.diff_logs(py.records, py.records) is None
    # byte-parity invariant: jax run units line up with oracle branches
    assert obs_audit.diff_logs(py.records, jx.records) is None


def test_diff_logs_localizes_tampered_decision():
    import copy

    with obs_audit.capture() as sinks:
        _single("python").consensus()
    records = sinks[0].records
    tampered = copy.deepcopy(records)
    victim = next(r for r in tampered if r["kind"] == "branch")
    syms = bytearray(obs_audit.unb64(victim["syms"]))
    syms[0] = (syms[0] + 1) % 256
    victim["syms"] = obs_audit.b64(bytes(sorted(syms)))
    detail = obs_audit.diff_logs(records, tampered)
    assert detail is not None
    assert detail["pop_a"] == victim["pop"]
    assert detail["key"][1] == victim["len"]
    assert detail["value_a"] != detail["value_b"]


# --------------------------------------------------- lockstep shadowing


def test_clean_shadow_single_and_dual():
    obs_flight.reset()
    obs_audit.reset_stats()
    with obs_audit.shadow_override("python"):
        single = _single("jax").consensus()
        dual = _dual("jax").consensus()
    assert single and dual
    snap = obs_audit.stats_snapshot()
    assert snap["divergences"] == 0
    assert snap["shadow_pops"] > 0
    assert not [
        i for i in obs_flight.incidents()
        if i.get("reason") == "parity_divergence"
    ]


def test_shadow_noop_for_python_backend():
    obs_audit.reset_stats()
    with obs_audit.shadow_override("python"):
        _single("python").consensus()  # oracle IS the primary: no shadow
    assert obs_audit.stats_snapshot()["shadow_pops"] == 0


def test_seeded_flip_vote_aborts_shadow_once(faults):
    # find where the jax engine commits a forced run, then flip that vote
    with obs_audit.capture(strict_align=True) as sinks:
        _single("jax").consensus()
    runs = [
        r for r in sinks[0].records
        if r["kind"] == "run" and r.get("forced")
    ]
    assert runs, "workload produced no forced device runs"
    length = runs[0]["len"]
    faults.add("flip_vote", backend="jax", op="vote", at=length, count=1)
    obs_flight.reset()
    obs_audit.reset_stats()
    with pytest.raises(obs_audit.ParityDivergence) as err:
        with obs_audit.shadow_override("python"):
            _single("jax").consensus()
    detail = err.value.detail
    assert detail["key"][0] == "s" and detail["key"][1] == length
    assert detail["value_a"] != detail["value_b"]
    assert obs_audit.stats_snapshot()["divergences"] == 1
    incidents = [
        i for i in obs_flight.incidents()
        if i.get("reason") == "parity_divergence"
    ]
    assert len(incidents) == 1  # exactly one, despite streaming feeds


# ----------------------------------------------------- metrics & status


@pytest.fixture
def metrics_on():
    obs_metrics.enable_metrics(True)
    obs_metrics.registry().reset()
    try:
        yield
    finally:
        obs_metrics.reset_metrics_enabled()
        obs_metrics.registry().reset()


def test_audit_records_counter_when_metrics_on(metrics_on):
    with obs_audit.capture():
        _single("python").consensus()
    snap = obs_metrics.registry().snapshot()
    series = snap["waffle_audit_records_total"]["series"]
    assert series['{engine="single"}'] > 0


def test_status_none_when_fully_inactive():
    obs_audit.reset_stats()
    assert obs_audit.status() is None


def test_status_reports_activity():
    obs_audit.reset_stats()
    with obs_audit.capture():
        _single("python").consensus()
    status = obs_audit.status()
    assert status is not None
    assert status["records"] > 0
    assert status["enabled"] is False and status["shadow"] is None
