"""End-to-end tests for the single-consensus engine, mirroring the
reference suite (``/root/reference/src/consensus.rs:572-852``): exact
expected results including per-read scores, tie ordering, wildcards,
early termination, offset windows, and the coverage-gap error string."""

import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    Consensus,
    ConsensusDWFA,
    ConsensusCost,
)
from waffle_con_tpu.models.consensus import EngineError


def test_doc_example():
    cdwfa = ConsensusDWFA()
    for s in [b"ACGT", b"ACCGT", b"ACCCGT"]:
        cdwfa.add_sequence(s)
    consensus = cdwfa.consensus()
    assert len(consensus) == 1
    assert consensus[0].sequence == b"ACCGT"
    assert consensus[0].scores == [1, 0, 1]


def test_single_sequence():
    sequence = b"ACGTACGTACGT"
    cdwfa = ConsensusDWFA()
    cdwfa.add_sequence(sequence)
    assert len(cdwfa.alphabet) == 4
    assert cdwfa.consensus() == [
        Consensus(sequence, ConsensusCost.L1_DISTANCE, [0])
    ]


def test_dual_sequence_tie():
    sequence = b"ACGTACGTACGT"
    sequence2 = b"ACGTACCTACGT"
    cdwfa = ConsensusDWFA()
    cdwfa.add_sequence(sequence)
    cdwfa.add_sequence(sequence2)
    # tie between the two inputs; lexicographic result order
    assert cdwfa.consensus() == [
        Consensus(sequence2, ConsensusCost.L1_DISTANCE, [1, 0]),
        Consensus(sequence, ConsensusCost.L1_DISTANCE, [0, 1]),
    ]


def test_trio_sequence():
    sequence = b"ACGTACGTACGT"
    sequence2 = b"ACGTACCTACGT"
    cdwfa = ConsensusDWFA()
    cdwfa.add_sequence(sequence)
    cdwfa.add_sequence(sequence)
    cdwfa.add_sequence(sequence2)
    assert cdwfa.consensus() == [
        Consensus(sequence, ConsensusCost.L1_DISTANCE, [0, 0, 1])
    ]


def test_complicated():
    expected = b"ACGTACGTACGT"
    sequences = [b"ACTACGGTACGT", b"ACGTAAGTCCGT", b"AAGTACGTACGT"]
    cdwfa = ConsensusDWFA()
    for s in sequences:
        cdwfa.add_sequence(s)
    consensus = cdwfa.consensus()
    assert len(consensus) == 1
    assert consensus[0].sequence == expected


def test_wildcards():
    expected = b"ACGTACGTACGT"
    sequences = [b"ACGTACCGT****", b"**GTATGTAC**", b"****ACGTACGT"]
    cdwfa = ConsensusDWFA(
        CdwfaConfigBuilder().wildcard(ord("*")).build()
    )
    for s in sequences:
        cdwfa.add_sequence(s)
    assert len(cdwfa.alphabet) == 4
    consensus = cdwfa.consensus()
    assert len(consensus) == 1
    assert consensus[0].sequence == expected
    assert consensus[0].scores == [1, 1, 0]


def test_all_wildcards():
    actual_consensus = b"*CGTACG*ACG*"
    sequences = [b"*CGTAACG*ACG*", b"*CGTACG*ACG*", b"*CGTACG*ATG*"]
    cdwfa = ConsensusDWFA(
        CdwfaConfigBuilder().wildcard(ord("*")).build()
    )
    for s in sequences:
        cdwfa.add_sequence(s)
    consensus = cdwfa.consensus()
    assert len(consensus) == 1
    assert consensus[0].sequence == actual_consensus
    assert consensus[0].scores == [1, 0, 1]


def test_allow_early_termination_costs():
    expected = b"ACGT"
    # without early termination a prefix ladder cannot recover the full
    # sequence
    cdwfa = ConsensusDWFA(
        CdwfaConfigBuilder().wildcard(ord("*")).build()
    )
    for i in range(1, len(expected) + 1):
        cdwfa.add_sequence(expected[:i])
    assert cdwfa.consensus() == [
        Consensus(b"AC", ConsensusCost.L1_DISTANCE, [1, 0, 1, 2]),
        Consensus(b"ACG", ConsensusCost.L1_DISTANCE, [2, 1, 0, 1]),
    ]

    # with early termination the full sequence is free for short reads
    cdwfa = ConsensusDWFA(
        CdwfaConfigBuilder()
        .wildcard(ord("*"))
        .allow_early_termination(True)
        .build()
    )
    for i in range(1, len(expected) + 1):
        cdwfa.add_sequence(expected[:i])
    assert cdwfa.consensus() == [
        Consensus(expected, ConsensusCost.L1_DISTANCE, [0, 0, 0, 0])
    ]


def test_offset_windows():
    expected = b"ACGTACGTACGTACGT"
    sequences = [b"ACGTACGTACGTACGT", b"ACGTACGTACGT", b"GTACGTACGT"]
    offsets = [None, 4, 7]
    cdwfa = ConsensusDWFA(
        CdwfaConfigBuilder()
        .offset_window(1)
        .offset_compare_length(4)
        .build()
    )
    for sequence, offset in zip(sequences, offsets):
        cdwfa.add_sequence_offset(sequence, offset)
    consensus = cdwfa.consensus()
    assert len(consensus) == 1
    assert consensus[0].sequence == expected
    assert consensus[0].scores == [0, 0, 0]


def test_offset_gap_err():
    sequences = [b"ACGTACGTACGTACGT", b"ACGTACGTACGTACGT"]
    offsets = [None, 1000]
    cdwfa = ConsensusDWFA(
        CdwfaConfigBuilder()
        .offset_window(1)
        .offset_compare_length(4)
        .build()
    )
    for sequence, offset in zip(sequences, offsets):
        cdwfa.add_sequence_offset(sequence, offset)
    with pytest.raises(EngineError) as err:
        cdwfa.consensus()
    assert str(err.value) == "Finalize called on DWFA that was never initialized."


def test_no_initial_sequence_err():
    cdwfa = ConsensusDWFA(
        CdwfaConfigBuilder().auto_shift_offsets(False).build()
    )
    cdwfa.add_sequence_offset(b"ACGT", 10)
    with pytest.raises(EngineError) as err:
        cdwfa.consensus()
    assert (
        str(err.value)
        == "Must have at least one initial offset of None to see the consensus."
    )


def test_l2_cost():
    sequence = b"ACGTACGTACGT"
    sequence2 = b"ACGTACCTACGT"
    cdwfa = ConsensusDWFA(
        CdwfaConfigBuilder()
        .consensus_cost(ConsensusCost.L2_DISTANCE)
        .build()
    )
    cdwfa.add_sequence(sequence)
    cdwfa.add_sequence(sequence)
    cdwfa.add_sequence(sequence2)
    assert cdwfa.consensus() == [
        Consensus(sequence, ConsensusCost.L2_DISTANCE, [0, 0, 1])
    ]


def test_coverage_gap_message_all_backends():
    """The coverage-gap error string carries both lengths on every
    backend, exactly as the reference formats it
    (``/root/reference/src/consensus.rs:305``) — including the full C++
    engine, whose C ABI ships the two numbers in an error-detail blob
    (VERDICT r3 #8)."""
    from waffle_con_tpu.native import native_consensus

    expected = (
        "Encountered coverage gap: consensus is length 2 with no "
        "candidates, but sequences activate at 40"
    )

    def cfg(backend):
        return (
            CdwfaConfigBuilder()
            .allow_early_termination(True)
            .offset_window(4)
            .offset_compare_length(10)
            .min_count(1)
            .backend(backend)
            .build()
        )

    for backend in ("python", "jax", "native"):
        engine = ConsensusDWFA(cfg(backend))
        engine.add_sequence_offset(b"AA", None)
        engine.add_sequence_offset(b"CC", 30)
        with pytest.raises(EngineError) as err:
            engine.consensus()
        assert str(err.value) == expected, backend

    # the full C++ engine path (search loop in C++, not just the scorer)
    with pytest.raises(EngineError) as err:
        native_consensus([b"AA", b"CC"], offsets=[None, 30], config=cfg("native"))
    assert str(err.value) == expected
