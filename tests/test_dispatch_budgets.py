"""Fast-path engagement regression net: per scenario family, the
blocking-dispatch profile is pinned.

Speculative K-column stepping, the dual kernel, and the ragged arena
all exist to keep the Python↔device round-trip count flat; a refactor
that silently disengages one of them shows up here as a budget bust
(every dispatch family counter is in
``ops.scorer.DISPATCH_COUNTER_KEYS``) long before it shows up as a
wall-clock regression on a noisy host.  Budgets are the counts
measured at WAFFLE_RUN_COLS=1 on the jax CPU backend with ~40%
headroom — they gate "an extra dispatch per step" regressions, not
single-call jitter.  ``run_pallas_calls`` must stay exactly zero on
CPU: the interpret-mode Pallas path engaging off-TPU is itself a bug.
"""

import numpy as np
import pytest

from waffle_con_tpu import (
    ConsensusDWFA,
    DualConsensusDWFA,
    PriorityConsensusDWFA,
)
from waffle_con_tpu.config import CdwfaConfigBuilder
from waffle_con_tpu.ops.scorer import DISPATCH_COUNTER_KEYS
from waffle_con_tpu.utils.example_gen import corrupt, generate_test

pytestmark = pytest.mark.serve


def _cfg(**kw):
    builder = (CdwfaConfigBuilder().backend("jax").min_count(2)
               .initial_band(16))
    for key, value in kw.items():
        builder = getattr(builder, key)(value)
    return builder.build()


def _dual_reads(seq_len, per_hap, split_at, seed=11):
    truth, reads1 = generate_test(4, seq_len, per_hap, 0.01, seed=seed)
    hap2 = bytearray(truth)
    for pos in split_at:
        hap2[pos] = (hap2[pos] + 1) % 4
    hap2 = bytes(hap2)
    reads2 = [corrupt(hap2, 0.01, np.random.default_rng(700 + i))
              for i in range(per_hap)]
    return list(reads1) + reads2


def _single_clean():
    _, reads = generate_test(4, 120, 6, 0.01, seed=5)
    engine = ConsensusDWFA(_cfg())
    for read in reads:
        engine.add_sequence(read)
    return engine


def _dual_split():
    engine = DualConsensusDWFA(_cfg())
    for read in _dual_reads(80, 4, (30, 60)):
        engine.add_sequence(read)
    return engine


def _locked_tail():
    # haplotypes diverge only near the end: both branches lock a long
    # shared prefix before the dual split engages
    engine = DualConsensusDWFA(_cfg())
    for read in _dual_reads(150, 4, (140, 145)):
        engine.add_sequence(read)
    return engine


def _min_af():
    engine = DualConsensusDWFA(_cfg(min_af=0.25))
    for read in _dual_reads(80, 4, (30, 60), seed=13):
        engine.add_sequence(read)
    return engine


def _priority_chain():
    _, level0 = generate_test(4, 60, 4, 0.01, seed=3)
    t1a, _ = generate_test(4, 80, 1, 0.0, seed=4)
    t1b = bytearray(t1a)
    t1b[30] = (t1b[30] + 1) % 4
    t1b[60] = (t1b[60] + 2) % 4
    t1b = bytes(t1b)
    engine = PriorityConsensusDWFA(_cfg())
    for i in range(4):
        level1 = corrupt(t1a if i < 2 else t1b, 0.01,
                         np.random.default_rng(200 + i))
        engine.add_sequence_chain([level0[i], level1])
    return engine


# (build, total-dispatch budget) — measured totals: 1/14/6/14/63
_FAMILIES = {
    "single_clean": (_single_clean, 2),
    "dual_split": (_dual_split, 19),
    "locked_tail": (_locked_tail, 9),
    "min_af": (_min_af, 19),
    "priority_chain": (_priority_chain, 85),
}


@pytest.mark.parametrize("family", sorted(_FAMILIES))
def test_blocking_dispatch_budget(family):
    build, budget = _FAMILIES[family]
    engine = build()
    assert engine.consensus()  # the scenario must actually resolve
    counters = engine.last_search_stats["scorer_counters"]
    total = sum(counters.get(key, 0) for key in DISPATCH_COUNTER_KEYS)
    assert 0 < total <= budget, (
        f"{family}: {total} blocking dispatches > budget {budget} "
        f"({ {k: v for k, v in sorted(counters.items()) if v} })"
    )
    # the batched device loop must be engaged, not degenerated into
    # per-step host round-trips
    steps = (counters.get("run_steps", 0)
             + counters.get("run_dual_steps", 0)
             + counters.get("arena_steps", 0))
    assert steps > total, (family, steps, total)
    # interpret-mode Pallas must never engage on the CPU backend
    pallas = (counters.get("run_pallas_calls", 0)
              + counters.get("run_dual_pallas_calls", 0))
    assert pallas == 0, (family, pallas)
