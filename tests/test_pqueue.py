"""Tests for the queue tracker (parity with
``/root/reference/src/pqueue_tracker.rs:150-171``) and the set-semantics
priority queue."""

import pytest

from waffle_con_tpu.utils.pqueue import (
    CapacityFullError,
    PQueueTracker,
    SetPriorityQueue,
)


def test_basic_capacity():
    tracker = PQueueTracker(0, 2)
    assert not tracker.at_capacity(1)
    assert tracker.processed(1) == 0
    tracker.process(1)
    assert not tracker.at_capacity(1)
    assert tracker.processed(1) == 1
    tracker.process(1)
    assert tracker.at_capacity(1)
    assert tracker.processed(1) == 2
    with pytest.raises(CapacityFullError):
        tracker.process(1)
    assert tracker.processed(1) == 2


def test_threshold_accounting():
    tracker = PQueueTracker(4, 10)
    for v in [0, 0, 1, 2, 5]:
        tracker.insert(v)
    assert len(tracker) == 5
    assert tracker.unfiltered_len() == 5
    tracker.increment_threshold()  # drop the two zeros
    assert len(tracker) == 3
    assert tracker.unfiltered_len() == 5
    tracker.increase_threshold(3)  # drop 1 and 2
    assert len(tracker) == 1
    tracker.remove(0)  # below threshold: unfiltered only
    assert len(tracker) == 1
    assert tracker.unfiltered_len() == 4
    tracker.remove(5)
    assert len(tracker) == 0
    assert tracker.occupancy(0) == 1
    assert tracker.occupancy(5) == 0
    assert tracker.threshold() == 3


def test_set_priority_queue_order():
    q = SetPriorityQueue()
    q.push("a", "a", (-3, 0))
    q.push("b", "b", (-1, 0))
    q.push("c", "c", (-1, 5))
    q.push("d", "d", (-1, 5))
    # best: lowest cost, then longest, then FIFO
    assert q.pop()[0] == "c"
    assert q.pop()[0] == "d"
    assert q.pop()[0] == "b"
    assert q.pop()[0] == "a"
    assert q.is_empty()
    with pytest.raises(IndexError):
        q.pop()


def test_set_priority_queue_duplicate_rejected():
    q = SetPriorityQueue()
    assert q.push("k", 1, (0, 0))
    # a duplicate key is rejected; the original entry stays queued
    assert not q.push("k", 2, (0, 0))
    assert len(q) == 1
    item, _ = q.pop()
    assert item == 1
    assert q.is_empty()
