"""Tests for the queue tracker (parity with
``/root/reference/src/pqueue_tracker.rs:150-171``) and the set-semantics
priority queue."""

import numpy as np
import pytest

from waffle_con_tpu.utils.pqueue import (
    CapacityFullError,
    PQueueTracker,
    SetPriorityQueue,
)


def test_basic_capacity():
    tracker = PQueueTracker(0, 2)
    assert not tracker.at_capacity(1)
    assert tracker.processed(1) == 0
    tracker.process(1)
    assert not tracker.at_capacity(1)
    assert tracker.processed(1) == 1
    tracker.process(1)
    assert tracker.at_capacity(1)
    assert tracker.processed(1) == 2
    with pytest.raises(CapacityFullError):
        tracker.process(1)
    assert tracker.processed(1) == 2


def test_threshold_accounting():
    tracker = PQueueTracker(4, 10)
    for v in [0, 0, 1, 2, 5]:
        tracker.insert(v)
    assert len(tracker) == 5
    assert tracker.unfiltered_len() == 5
    tracker.increment_threshold()  # drop the two zeros
    assert len(tracker) == 3
    assert tracker.unfiltered_len() == 5
    tracker.increase_threshold(3)  # drop 1 and 2
    assert len(tracker) == 1
    tracker.remove(0)  # below threshold: unfiltered only
    assert len(tracker) == 1
    assert tracker.unfiltered_len() == 4
    tracker.remove(5)
    assert len(tracker) == 0
    assert tracker.occupancy(0) == 1
    assert tracker.occupancy(5) == 0
    assert tracker.threshold() == 3


def test_set_priority_queue_order():
    q = SetPriorityQueue()
    q.push("a", "a", (-3, 0))
    q.push("b", "b", (-1, 0))
    q.push("c", "c", (-1, 5))
    q.push("d", "d", (-1, 5))
    # best: lowest cost, then longest, then FIFO
    assert q.pop()[0] == "c"
    assert q.pop()[0] == "d"
    assert q.pop()[0] == "b"
    assert q.pop()[0] == "a"
    assert q.is_empty()
    with pytest.raises(IndexError):
        q.pop()


def test_set_priority_queue_duplicate_rejected():
    q = SetPriorityQueue()
    assert q.push("k", 1, (0, 0))
    # a duplicate key is rejected; the original entry stays queued
    assert not q.push("k", 2, (0, 0))
    assert len(q) == 1
    item, _ = q.pop()
    assert item == 1
    assert q.is_empty()


def test_replay_run_bookkeeping_fast_path_matches_scalar():
    """The vectorized run-replay (bulk_run_advance segments) must leave
    the tracker in exactly the state of the scalar per-step loop, across
    constriction triggers, queue pressure, and capacity edges."""
    import copy

    from waffle_con_tpu.config import CdwfaConfig
    from waffle_con_tpu.models.consensus import replay_run_bookkeeping

    rng = np.random.default_rng(7)

    def scalar_reference(tracker, cfg, top_len, steps, far, lcon):
        for j in range(steps):
            length = top_len + j
            if j > 0:
                while (
                    len(tracker) > cfg.max_queue_size
                    or lcon >= cfg.max_nodes_wo_constraint
                ) and tracker.threshold() < far:
                    tracker.increment_threshold()
                    lcon = 0
                tracker.remove(length)
            far = max(far, length)
            lcon += 1
            tracker.process(length)
            tracker.insert(length + 1)
        return far, lcon

    for trial in range(200):
        cfg = CdwfaConfig(
            max_queue_size=int(rng.integers(1, 6)),
            max_capacity_per_size=int(rng.integers(1, 5)),
            max_nodes_wo_constraint=int(rng.integers(2, 12)),
        )
        tr = PQueueTracker(64, cfg.max_capacity_per_size)
        # random pre-existing queue population and processing history
        for _ in range(int(rng.integers(0, 8))):
            tr.insert(int(rng.integers(0, 20)))
        for _ in range(int(rng.integers(0, 6))):
            v = int(rng.integers(0, 10))
            if not tr.at_capacity(v):
                tr.process(v)
        thr0 = int(rng.integers(0, 3))
        tr.increase_threshold(thr0)
        top_len = int(rng.integers(thr0, thr0 + 6))
        tr.insert(top_len)
        tr.remove(top_len)  # the in-hand pop
        far = top_len + int(rng.integers(0, 4))
        lcon = int(rng.integers(0, cfg.max_nodes_wo_constraint))
        steps = int(rng.integers(1, 30))

        ref = copy.deepcopy(tr)
        try:
            want_far, want_lcon = scalar_reference(
                ref, cfg, top_len, steps, far, lcon
            )
        except CapacityFullError:
            # engines bound steps so this cannot arise for them; the
            # fast path must surface the same error
            with pytest.raises(CapacityFullError):
                replay_run_bookkeeping(tr, cfg, top_len, steps, far, lcon)
            continue
        got_far, got_lcon = replay_run_bookkeeping(
            tr, cfg, top_len, steps, far, lcon
        )
        assert (got_far, got_lcon) == (want_far, want_lcon), trial
        assert tr._length_counts == ref._length_counts, trial
        assert tr._processed_counts == ref._processed_counts, trial
        assert tr._total_count == ref._total_count, trial
        assert tr.threshold() == ref.threshold(), trial
