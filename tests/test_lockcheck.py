"""Runtime lock-order checker contract tests.

The claims under test: the checked proxies are behavior-transparent
(acquire/release/context-manager semantics identical to the plain
primitives), zero-cost when disabled (plain ``threading`` objects come
back), and — the point of the subsystem — a *seeded inversion* (A→B on
one path, B→A on another) raises :class:`LockOrderError` and dumps a
flight incident even though the two paths never actually deadlock.
"""

import threading

import pytest

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.analysis.lockcheck import LockOrderError
from waffle_con_tpu.obs import flight as obs_flight


@pytest.fixture
def checked():
    """Force-enable lockcheck for the test, restore + clear after."""
    lockcheck.enable_lockcheck(True)
    lockcheck.reset()
    yield
    lockcheck.reset()
    lockcheck.reset_enabled()


def test_disabled_factories_return_plain_primitives():
    lockcheck.enable_lockcheck(False)
    try:
        lock = lockcheck.make_lock("t.plain")
        rlock = lockcheck.make_rlock("t.plain_r")
        assert isinstance(lock, type(threading.Lock()))
        # RLock's concrete type varies; the proxy it must NOT be
        assert not isinstance(rlock, lockcheck._CheckedLock)
    finally:
        lockcheck.reset_enabled()


def test_proxy_is_behavior_transparent(checked):
    lock = lockcheck.make_lock("t.transparent")
    assert isinstance(lock, lockcheck._CheckedLock)
    assert not lock.locked()
    with lock:
        assert lock.locked()
    assert not lock.locked()
    assert lock.acquire(blocking=False)
    assert not lock.acquire(blocking=False)  # already held
    lock.release()


def test_consistent_order_records_edges_without_error(checked):
    a = lockcheck.make_lock("t.A")
    b = lockcheck.make_lock("t.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert ("t.A", "t.B") in lockcheck.edges()
    assert ("t.B", "t.A") not in lockcheck.edges()


def test_seeded_inversion_raises(checked):
    """A→B established, then B→A attempted: the checker fires on the
    second *order*, not on an actual deadlock (single thread here)."""
    a = lockcheck.make_lock("t.inv_A")
    b = lockcheck.make_lock("t.inv_B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderError) as excinfo:
            a.acquire()
    assert "t.inv_A" in str(excinfo.value)
    assert "t.inv_B" in str(excinfo.value)


def test_inversion_detected_across_threads(checked):
    """The deadlock-shaped schedule, serialized so it cannot hang:
    thread 1 does A→B, thread 2 then does B→A and must get the error."""
    a = lockcheck.make_lock("t.x_A")
    b = lockcheck.make_lock("t.x_B")

    def first():
        with a:
            with b:
                pass

    t = threading.Thread(target=first)
    t.start()
    t.join()

    caught = []

    def second():
        try:
            with b:
                a.acquire()
        except LockOrderError as exc:
            caught.append(exc)

    t2 = threading.Thread(target=second)
    t2.start()
    t2.join()
    assert len(caught) == 1


def test_transitive_inversion_raises(checked):
    """A→B plus B→C established; C→A must fire (cycle through B)."""
    a = lockcheck.make_lock("t.tr_A")
    b = lockcheck.make_lock("t.tr_B")
    c = lockcheck.make_lock("t.tr_C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(LockOrderError):
            a.acquire()


def test_inversion_dumps_flight_incident(checked):
    obs_flight.reset()
    a = lockcheck.make_lock("t.fl_A")
    b = lockcheck.make_lock("t.fl_B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderError):
            a.acquire()
    reasons = [i.get("reason") for i in obs_flight.incidents()]
    assert "lock_order_inversion" in reasons


def test_rlock_reentry_and_sibling_instances_ok(checked):
    r = lockcheck.make_rlock("t.re_R")
    with r:
        with r:  # reentrant: no self-wait edge, no error
            pass
    # two instances sharing a creation site: nested acquire allowed
    # (instance-ordered siblings are a legitimate pattern)
    j1 = lockcheck.make_lock("t.sib")
    j2 = lockcheck.make_lock("t.sib")
    with j1:
        with j2:
            pass
    assert ("t.sib", "t.sib") not in lockcheck.edges()


def test_nonblocking_acquire_records_no_edges(checked):
    a = lockcheck.make_lock("t.nb_A")
    b = lockcheck.make_lock("t.nb_B")
    with a:
        assert b.acquire(blocking=False)
        b.release()
    assert ("t.nb_A", "t.nb_B") not in lockcheck.edges()


def test_make_thread_passthrough():
    hits = []
    t = lockcheck.make_thread(target=lambda: hits.append(1),
                              name="t-pass", daemon=True)
    t.start()
    t.join()
    assert hits == [1]


def test_served_job_runs_clean_under_lockcheck(checked):
    """The serve stack (service/job/dispatcher/flight/metrics locks all
    created after enabling) completes a job with the checker armed —
    the lock web is inversion-free end to end."""
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.serve import (
        ConsensusService, JobRequest, ServeConfig,
    )
    from waffle_con_tpu.serve.service import _build_engine

    cfg = CdwfaConfigBuilder().backend("python").build()
    reads = (b"ACGTACGTAC",) * 4
    request = JobRequest(kind="single", reads=reads, config=cfg)
    service = ConsensusService(ServeConfig(workers=2))
    try:
        handle = service.submit(request)
        result = handle.result(timeout=60.0)
    finally:
        service.close()
    assert result == _build_engine(request).consensus()
