"""Tests for the MultiConsensus result type
(parity: ``/root/reference/src/multi_consensus.rs:73-94``)."""

from waffle_con_tpu import Consensus, ConsensusCost, MultiConsensus


def test_multiconsensus_sort():
    consensuses = [
        Consensus(b"ACGT", ConsensusCost.L1_DISTANCE, [0]),
        Consensus(b"TGCA", ConsensusCost.L1_DISTANCE, [0]),
        Consensus(b"AAAA", ConsensusCost.L1_DISTANCE, [0]),
    ]
    multicon = MultiConsensus(consensuses, [2, 0, 1])
    assert multicon.consensuses == [
        Consensus(b"AAAA", ConsensusCost.L1_DISTANCE, [0]),
        Consensus(b"ACGT", ConsensusCost.L1_DISTANCE, [0]),
        Consensus(b"TGCA", ConsensusCost.L1_DISTANCE, [0]),
    ]
    assert multicon.sequence_indices == [0, 1, 2]
