"""Serve-layer contract tests.

Core claim under test: N concurrent mixed-engine jobs through
:class:`ConsensusService` return results **byte-identical** to serial
execution of the same requests (golden fixtures included), while the
cross-job :class:`BatchingDispatcher` actually coalesces (mean batch
occupancy > 1 under concurrent load).  Plus the scheduling semantics:
bounded queue rejects typed-and-fast when full, priorities pop first
(FIFO within a class), deadlines and cancellation abort at dispatch
boundaries, and fault-injected backend demotion works inside a served
job exactly as it does serially.
"""

import time

import pytest

from waffle_con_tpu import CdwfaConfigBuilder
from waffle_con_tpu.runtime import events
from waffle_con_tpu.serve import (
    ConsensusService,
    DeadlineExceeded,
    JobCancelled,
    JobRequest,
    JobStatus,
    ServeConfig,
    ServiceClosed,
    ServiceOverloaded,
)
from waffle_con_tpu.serve.service import _build_engine
from waffle_con_tpu.utils.example_gen import generate_test
from waffle_con_tpu.utils.fixtures import (
    load_dual_fixture,
    load_priority_fixture,
)

pytestmark = pytest.mark.serve

DUAL_READS = (b"ACGTACGT", b"ACGTACGT", b"ACTTACGT", b"ACTTACGT")


def _cfg(**kw):
    b = CdwfaConfigBuilder().backend("python")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _fixture_cfg():
    return _cfg(wildcard=ord("*"))


def _serial(request: JobRequest):
    """The serial reference: same construction path as the service,
    no decorator installed, run on the calling thread."""
    return _build_engine(request).consensus()


def _mixed_requests():
    """Eight mixed-engine jobs: every golden fixture scenario plus
    synthetic single/dual workloads."""
    fcfg = _fixture_cfg()
    requests = []
    sequences, _ = load_dual_fixture("dual_001", True, fcfg.consensus_cost)
    requests.append(
        JobRequest(kind="dual", reads=tuple(sequences), config=fcfg)
    )
    for name, include in (
        ("multi_exact_001", True),
        ("multi_err_001", False),
        ("multi_samesplit_001", True),
        ("priority_001", True),
    ):
        chains, _ = load_priority_fixture(name, include, fcfg.consensus_cost)
        requests.append(
            JobRequest(
                kind="priority",
                reads=tuple(tuple(c) for c in chains),
                config=fcfg,
                tag=name,
            )
        )
    scfg = _cfg(min_count=2)
    for seed in (0, 1):
        _, reads = generate_test(4, 160, 6, 0.02, seed=seed)
        requests.append(
            JobRequest(kind="single", reads=tuple(reads), config=scfg)
        )
    requests.append(
        JobRequest(kind="dual", reads=DUAL_READS, config=_cfg(min_count=1))
    )
    return requests


# ------------------------------------------------ parity (the tentpole)


def test_concurrent_mixed_jobs_byte_identical_to_serial():
    requests = _mixed_requests()
    assert len(requests) >= 8
    expected = [_serial(r) for r in requests]

    with ConsensusService(
        ServeConfig(workers=4, batch_window_s=0.02)
    ) as svc:
        handles = svc.submit_all(requests)
        results = [h.result(timeout=300) for h in handles]
        stats = svc.stats()

    for req, got, want in zip(requests, results, expected):
        assert got == want, f"served {req.kind} job diverged from serial"
    assert stats["jobs"]["done"] == len(requests)
    assert stats["jobs"]["failed"] == 0

    # the fixture scenarios also match their golden expectations
    fcfg = _fixture_cfg()
    _, dual_expected = load_dual_fixture("dual_001", True, fcfg.consensus_cost)
    assert results[0] == [dual_expected]
    for req, got in zip(requests[1:5], results[1:5]):
        chains, want = load_priority_fixture(
            req.tag, req.tag != "multi_err_001", fcfg.consensus_cost
        )
        assert got.sequence_indices == want.sequence_indices
        assert [[c.sequence for c in chain] for chain in got.consensuses] == [
            [c.sequence for c in chain] for chain in want.consensuses
        ]


def test_batch_occupancy_above_one_under_concurrent_load():
    cfg = _cfg(min_count=2)
    _, reads = generate_test(4, 150, 6, 0.02, seed=3)
    expected = None
    with ConsensusService(
        ServeConfig(workers=8, batch_window_s=0.05, max_batch=8)
    ) as svc:
        handles = svc.submit_all(
            [JobRequest(kind="single", reads=tuple(reads), config=cfg)
             for _ in range(8)]
        )
        results = [h.result(timeout=300) for h in handles]
        dispatch = svc.stats()["dispatch"]
    expected = _serial(
        JobRequest(kind="single", reads=tuple(reads), config=cfg)
    )
    assert all(r == expected for r in results)
    # identical jobs share one shape bucket: with 8 workers and a
    # generous window the dispatcher must actually coalesce
    assert dispatch["coalesced_batches"] > 0
    assert dispatch["mean_batch_occupancy"] > 1.0


# ------------------------------------------------ admission / backpressure


def test_full_queue_rejects_typed_not_blocking():
    cfg = _cfg(min_count=1)
    req = JobRequest(kind="dual", reads=DUAL_READS, config=cfg)
    # workers parked: the queue fills deterministically
    svc = ConsensusService(
        ServeConfig(workers=2, queue_limit=2), autostart=False
    )
    h1 = svc.submit(req)
    h2 = svc.submit(req)
    t0 = time.monotonic()
    with pytest.raises(ServiceOverloaded):
        svc.submit(req)
    assert time.monotonic() - t0 < 1.0, "rejection must not block"
    assert svc.stats()["jobs"]["rejected"] == 1

    svc.start()
    assert h1.result(timeout=120) == h2.result(timeout=120)
    svc.close()
    with pytest.raises(ServiceClosed):
        svc.submit(req)


def test_priority_classes_fifo_within_class():
    cfg = _cfg(min_count=1)
    req = lambda prio: JobRequest(
        kind="dual", reads=DUAL_READS, config=cfg, priority=prio
    )
    svc = ConsensusService(ServeConfig(workers=1), autostart=False)
    low_a = svc.submit(req(0))
    low_b = svc.submit(req(0))
    high = svc.submit(req(5))
    svc.start()
    for h in (low_a, low_b, high):
        h.result(timeout=120)
    svc.close()
    assert high.started_at < low_a.started_at < low_b.started_at


# ------------------------------------------------ deadlines / cancellation


def test_cancel_queued_job_finalizes_immediately():
    cfg = _cfg(min_count=1)
    req = JobRequest(kind="dual", reads=DUAL_READS, config=cfg)
    svc = ConsensusService(ServeConfig(workers=1), autostart=False)
    keep = svc.submit(req)
    doomed = svc.submit(req)
    assert doomed.cancel()
    assert doomed.status is JobStatus.CANCELLED
    with pytest.raises(JobCancelled):
        doomed.result(timeout=0)
    assert not doomed.cancel(), "second cancel reports already-terminal"
    svc.start()
    assert keep.result(timeout=120)
    svc.close()
    assert svc.stats()["jobs"]["cancelled"] == 1


def test_cancel_mid_run_aborts_at_dispatch_boundary():
    cfg = _cfg(min_count=2)
    _, reads = generate_test(4, 1500, 12, 0.04, seed=2)  # ~seconds of work
    with ConsensusService(ServeConfig(workers=1)) as svc:
        h = svc.submit(
            JobRequest(kind="single", reads=tuple(reads), config=cfg)
        )
        assert h.wait_running(30)
        time.sleep(0.2)
        assert h.cancel()
        with pytest.raises(JobCancelled):
            h.result(timeout=60)
        assert h.status is JobStatus.CANCELLED


def test_deadline_lapsed_in_queue_expires_at_pop():
    cfg = _cfg(min_count=1)
    svc = ConsensusService(ServeConfig(workers=1), autostart=False)
    h = svc.submit(
        JobRequest(
            kind="dual", reads=DUAL_READS, config=cfg, deadline_s=0.01
        )
    )
    time.sleep(0.05)
    svc.start()
    with pytest.raises(DeadlineExceeded):
        h.result(timeout=60)
    assert h.status is JobStatus.EXPIRED
    svc.close()
    assert svc.stats()["jobs"]["expired"] == 1
    assert events.get_events("deadline_exceeded")


def test_deadline_mid_run_expires_at_dispatch_boundary():
    cfg = _cfg(min_count=2)
    _, reads = generate_test(4, 1500, 12, 0.04, seed=2)
    with ConsensusService(ServeConfig(workers=1)) as svc:
        h = svc.submit(
            JobRequest(
                kind="single", reads=tuple(reads), config=cfg,
                deadline_s=0.4,
            )
        )
        with pytest.raises(DeadlineExceeded):
            h.result(timeout=60)
        assert h.status is JobStatus.EXPIRED


# ------------------------------------------------ fault tolerance composes


@pytest.mark.faultinject
def test_backend_demotion_inside_served_job(faults):
    """A supervised job served concurrently still demotes jax -> python
    mid-search on injected faults, byte-identical to the unfaulted run."""
    def cfg(**kw):
        b = CdwfaConfigBuilder().min_count(1).backend("jax")
        for k, v in kw.items():
            b = getattr(b, k)(v)
        return b.build()

    reads = (b"ACGTACGTACGT", b"ACGTACGTACGT", b"ACCTACGTACGT")
    expected = _serial(JobRequest(kind="single", reads=reads, config=cfg()))

    faults.add("timeout", backend="jax", at=3, count=None)
    faults.add("timeout", backend="jax", at=4, count=None)
    sup = cfg(
        backend_chain=("python",), dispatch_retries=1,
        breaker_threshold=2, retry_backoff_s=0.0,
    )
    with ConsensusService(ServeConfig(workers=2)) as svc:
        h = svc.submit(JobRequest(kind="single", reads=reads, config=sup))
        got = h.result(timeout=300)
    demotions = events.get_events("backend_demoted")
    assert [(d["from_backend"], d["to_backend"]) for d in demotions] == [
        ("jax", "python")
    ]
    assert [(c.sequence, c.scores) for c in got] == [
        (c.sequence, c.scores) for c in expected
    ]


# ------------------------------------------------ serve metrics


def test_serve_metrics_emitted():
    from waffle_con_tpu.obs import metrics as obs_metrics

    obs_metrics.enable_metrics(True)
    obs_metrics.registry().reset()
    try:
        cfg = _cfg(min_count=2)
        _, reads = generate_test(4, 120, 6, 0.02, seed=4)
        with ConsensusService(
            ServeConfig(workers=4, batch_window_s=0.05, queue_limit=2)
        ) as svc:
            handles = svc.submit_all(
                [JobRequest(kind="single", reads=tuple(reads), config=cfg)
                 for _ in range(2)]
            )
            for h in handles:
                h.result(timeout=300)
        snap = obs_metrics.registry().snapshot()
    finally:
        obs_metrics.registry().reset()
        obs_metrics.reset_metrics_enabled()

    assert "waffle_serve_queue_depth" in snap
    jobs_total = snap["waffle_serve_jobs_total"]["series"]
    assert sum(
        v for k, v in jobs_total.items() if 'outcome="done"' in k
    ) == 2
    assert "waffle_serve_job_latency_seconds" in snap
    occupancy = snap["waffle_serve_batch_occupancy"]["series"]
    assert sum(s["count"] for s in occupancy.values()) > 0


# ------------------------------------------- admission fairness (aging)


class _FakeClock:
    """Injectable monotonic clock for deterministic aging tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _queued_handle(job_id, priority):
    from waffle_con_tpu.serve.job import JobHandle

    return JobHandle(job_id, JobRequest(
        kind="dual", reads=DUAL_READS, priority=priority,
    ))


def test_aging_preserves_strict_priority_inside_window():
    from waffle_con_tpu.serve.scheduler import AdmissionQueue

    clk = _FakeClock()
    q = AdmissionQueue(10, aging_s=5.0, clock=clk)
    low = _queued_handle(0, priority=0)
    high = _queued_handle(1, priority=2)
    q.put(low)
    clk.t = 0.1
    q.put(high)
    # the low job has not aged: latency-sensitive traffic keeps its edge
    assert q.get(timeout=0) is high
    assert q.get(timeout=0) is low
    assert q.aged_pops == 0


def test_aged_low_priority_job_pops_through_a_high_flood():
    from waffle_con_tpu.serve.scheduler import AdmissionQueue

    clk = _FakeClock()
    q = AdmissionQueue(100, aging_s=1.0, clock=clk)
    low = _queued_handle(0, priority=0)
    q.put(low)
    highs = [_queued_handle(1 + i, priority=2) for i in range(50)]
    for h in highs:
        q.put(h)
    clk.t = 2.0  # the low job is now past the aging window
    assert q.get(timeout=0) is low
    assert q.aged_pops == 1
    # with the aged entry served, strict order resumes
    assert q.get(timeout=0) is highs[0]


def test_strict_priority_starves_without_aging():
    from waffle_con_tpu.serve.scheduler import AdmissionQueue

    clk = _FakeClock()
    q = AdmissionQueue(100, aging_s=None, clock=clk)
    low = _queued_handle(0, priority=0)
    q.put(low)
    highs = [_queued_handle(1 + i, priority=2) for i in range(5)]
    for h in highs:
        q.put(h)
    clk.t = 1e6  # any finite aging window would have fired by now
    assert [q.get(timeout=0) for _ in range(5)] == highs
    assert q.get(timeout=0) is low
    assert q.aged_pops == 0


def test_admission_aging_property_over_synthetic_trace():
    """Model-based fairness property: replay a random put/pop trace
    against a reference model.  At every pop the queue must return the
    strict-priority head UNLESS the oldest queued job has aged past the
    window (and is not already the head), in which case it must return
    that oldest job — so under arbitrary saturation no job ever waits
    more than ``aging_s`` plus one dispatch."""
    import numpy as np

    from waffle_con_tpu.serve.scheduler import AdmissionQueue

    rng = np.random.default_rng(7)
    clk = _FakeClock()
    aging = 0.5
    q = AdmissionQueue(1000, aging_s=aging, clock=clk)
    model = []  # entries mirror the heap tuples: (-prio, seq, t, handle)
    seq = 0
    aged_expected = 0
    for _ in range(400):
        clk.t += float(rng.exponential(0.05))
        if rng.random() < 0.6 or not model:
            prio = int(rng.integers(0, 3))
            h = _queued_handle(seq, prio)
            q.put(h)
            model.append((-prio, seq, clk.t, h))
            seq += 1
            continue
        head = min(model)
        oldest = min(model, key=lambda e: e[1])
        if clk.t - oldest[2] >= aging and oldest[1] != head[1]:
            expect = oldest
            aged_expected += 1
        else:
            expect = head
        got = q.get(timeout=0)
        assert got is expect[3], (
            f"pop at t={clk.t:.3f} returned job {got.job_id}, "
            f"model expected {expect[3].job_id}"
        )
        model.remove(expect)
    assert q.aged_pops == aged_expected
    assert aged_expected > 0, "trace never exercised the aging path"


def test_service_surfaces_aged_pops():
    cfg = _cfg(min_count=1)
    with ConsensusService(
        ServeConfig(workers=2, batch_window_s=0.0, aging_s=0.25)
    ) as svc:
        h = svc.submit(JobRequest(kind="dual", reads=DUAL_READS, config=cfg))
        h.result(timeout=120)
        stats = svc.stats()
    assert stats["aged_pops"] == 0  # no saturation, no aged pops


# --------------------------------------------- adaptive batch-window hold


def test_adaptive_hold_surfaced_and_bounded():
    cfg = _cfg(min_count=2)
    _, reads = generate_test(4, 150, 6, 0.02, seed=5)
    window_s = 0.05
    with ConsensusService(
        ServeConfig(workers=8, batch_window_s=window_s, max_batch=8)
    ) as svc:
        handles = svc.submit_all(
            [JobRequest(kind="single", reads=tuple(reads), config=cfg)
             for _ in range(8)]
        )
        for h in handles:
            h.result(timeout=300)
        dispatch = svc.stats()["dispatch"]
    assert dispatch["adaptive_window"] is True
    # the chosen hold is clamped to the configured window and to no
    # less than a quarter of it (the floor of the adaptive band)
    assert 0.0 < dispatch["last_hold_ms"] <= window_s * 1e3
    assert dispatch["mean_hold_ms"] <= window_s * 1e3
    # a burst of back-to-back submits leaves a warm (tiny) arrival EWMA
    assert dispatch["ewma_arrival_gap_ms"] is not None
    assert dispatch["ewma_arrival_gap_ms"] < window_s * 1e3


def test_adaptive_hold_off_uses_fixed_window():
    cfg = _cfg(min_count=2)
    _, reads = generate_test(4, 120, 6, 0.02, seed=6)
    window_s = 0.02
    with ConsensusService(
        ServeConfig(workers=4, batch_window_s=window_s,
                    adaptive_window=False)
    ) as svc:
        handles = svc.submit_all(
            [JobRequest(kind="single", reads=tuple(reads), config=cfg)
             for _ in range(4)]
        )
        for h in handles:
            h.result(timeout=300)
        dispatch = svc.stats()["dispatch"]
    assert dispatch["adaptive_window"] is False
    # with adaptation off every parked batch holds the full window
    assert dispatch["last_hold_ms"] == pytest.approx(window_s * 1e3)
    assert dispatch["mean_hold_ms"] == pytest.approx(window_s * 1e3)
