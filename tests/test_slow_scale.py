"""North-star-scale parity under the ``slow`` tier (VERDICT r3 #7).

Run with ``RUN_SLOW=1 python -m pytest tests/test_slow_scale.py`` (or
``-m slow``).  These exercise exactly what bench.py claims: generated
HiFi-like workloads at benchmark scale, jax backend vs the native C++
engine, exact result equality.  On the CPU jax backend the single case
takes ~30 s and the dual case several minutes.
"""

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.native import native_consensus, native_dual_consensus
from waffle_con_tpu.utils.example_gen import corrupt, generate_test


@pytest.mark.slow
def test_north_star_single_parity():
    """256 reads x 10 kb at 1% error — the headline bench config."""
    num_reads, seq_len, er = 256, 10_000, 0.01
    truth, reads = generate_test(4, seq_len, num_reads, er, seed=0)
    band = 16 + int(2 * er * seq_len)
    cfg = lambda b: (  # noqa: E731
        CdwfaConfigBuilder()
        .min_count(num_reads // 4)
        .backend(b)
        .initial_band(band)
        .build()
    )
    cpu = native_consensus(reads, config=cfg("native"))
    engine = ConsensusDWFA(cfg("jax"))
    for r in reads:
        engine.add_sequence(r)
    got = engine.consensus()
    assert [(c.sequence, c.scores) for c in got] == cpu
    assert got[0].sequence == truth
    counters = engine.last_search_stats["scorer_counters"]
    assert counters["grow_e_events"] == 0  # the band seed must hold


@pytest.mark.slow
def test_dual_scale_parity():
    """64 reads x 5 kb, two haplotypes differing by 3 SNPs."""
    num_reads, seq_len, er = 64, 5000, 0.01
    rng = np.random.default_rng(1)
    truth, reads1 = generate_test(4, seq_len, num_reads // 2, er, seed=1)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=3, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    h2 = bytes(h2)
    reads = list(reads1) + [
        corrupt(h2, er, np.random.default_rng(100 + i))
        for i in range(num_reads // 2)
    ]
    band = 16 + int(2 * er * seq_len)
    cfg = lambda b: (  # noqa: E731
        CdwfaConfigBuilder()
        .min_count(num_reads // 4)
        .backend(b)
        .initial_band(band)
        .build()
    )
    cpu = native_dual_consensus(reads, config=cfg("native"))
    engine = DualConsensusDWFA(cfg("jax"))
    for r in reads:
        engine.add_sequence(r)
    got = engine.consensus()
    assert got == cpu
    assert got[0].is_dual()


@pytest.mark.slow
def test_dual_locked_tail_scale_parity():
    """Different-length haplotypes at scale: the longer side keeps
    extending after the shorter finishes and locks — the record
    absorption + one-side-locked run path vs the C++ engine."""
    num_reads, seq_len, tail_len, er = 16, 2500, 500, 0.01
    rng = np.random.default_rng(5)
    truth, reads1 = generate_test(4, seq_len, num_reads // 2, er, seed=5)
    tail, _ = generate_test(4, tail_len, 1, 0.0, seed=6)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=2, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    h2 = bytes(h2) + tail
    reads = list(reads1) + [
        corrupt(h2, er, np.random.default_rng(600 + i))
        for i in range(num_reads // 2)
    ]
    band = 16 + int(2 * er * (seq_len + tail_len))
    cfg = lambda b: (  # noqa: E731
        CdwfaConfigBuilder()
        .min_count(num_reads // 4)
        .backend(b)
        .initial_band(band)
        .build()
    )
    cpu = native_dual_consensus(reads, config=cfg("native"))
    eng = DualConsensusDWFA(cfg("jax"))
    for r in reads:
        eng.add_sequence(r)
    got = eng.consensus()
    assert got == cpu
    # the scenario must actually exercise the locked-tail path: a dual
    # result whose sides differ in length
    assert got[0].is_dual()
    assert len(got[0].consensus1.sequence) != len(got[0].consensus2.sequence)
