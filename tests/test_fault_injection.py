"""Fault-injection harness: supervised dispatch under every fault class.

Each test arms a deterministic :class:`FaultPlan` (via the ``faults``
conftest fixture) and runs a real search through the
:class:`BackendSupervisor`.  The core contract under test: a mid-search
backend failure demotes the live search down the backend chain and the
final consensus is **byte-identical** (sequence AND scores) to an
uninterrupted run — for the single, dual, and priority engines alike.
"""

import logging
import os

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
    PriorityConsensusDWFA,
)
from waffle_con_tpu.ops.scorer import make_scorer
from waffle_con_tpu.runtime import events
from waffle_con_tpu.runtime import faults as faults_mod
from waffle_con_tpu.runtime.supervisor import (
    BackendFailure,
    BackendSupervisor,
    effective_chain,
)
from waffle_con_tpu.runtime.watchdog import WatchdogError, dispatch_total

pytestmark = pytest.mark.faultinject

SINGLE_READS = (b"ACGTACGTACGT", b"ACGTACGTACGT", b"ACCTACGTACGT")
DUAL_READS = (b"ACGTACGT", b"ACGTACGT", b"ACTTACGT", b"ACTTACGT")
PRIORITY_CHAINS = (
    [b"ACGT", b"ACGTACGT"],
    [b"ACGT", b"ACGTACGT"],
    [b"ACTT", b"ACTTACTT"],
    [b"ACTT", b"ACTTACTT"],
)


def _cfg(**kw):
    b = CdwfaConfigBuilder().min_count(1).backend("jax")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _sup_cfg(**kw):
    kw.setdefault("backend_chain", ("python",))
    kw.setdefault("dispatch_retries", 1)
    kw.setdefault("breaker_threshold", 2)
    kw.setdefault("retry_backoff_s", 0.0)
    return _cfg(**kw)


def _run_single(cfg, reads=SINGLE_READS):
    engine = ConsensusDWFA(cfg)
    for r in reads:
        engine.add_sequence(r)
    return engine, engine.consensus()


def _run_dual(cfg, reads=DUAL_READS):
    engine = DualConsensusDWFA(cfg)
    for r in reads:
        engine.add_sequence(r)
    return engine, engine.consensus()


def _run_priority(cfg, chains=PRIORITY_CHAINS):
    engine = PriorityConsensusDWFA(cfg)
    for c in chains:
        engine.add_sequence_chain(c)
    return engine, engine.consensus()


# ------------------------------------------------------------ chain/plan


def test_effective_chain_default():
    assert effective_chain(_cfg()) == ("jax", "native", "python")
    assert effective_chain(_cfg(backend="native")) == ("native", "python")


def test_effective_chain_explicit_starts_at_backend():
    cfg = _cfg(backend_chain=("python", "jax"))
    assert effective_chain(cfg) == ("jax", "python")


def test_plan_from_env_parsing():
    plan = faults_mod.plan_from_env("timeout:jax:*:5:1, device_loss:jax:run")
    assert len(plan.specs) == 2
    t, d = plan.specs
    assert (t.kind, t.backend, t.op, t.at, t.count) == (
        "timeout", "jax", "*", 5, 1,
    )
    # omitted at/count fields mean unlimited (None), not the API default
    assert (d.kind, d.backend, d.op, d.at, d.count) == (
        "device_loss", "jax", "run", None, None,
    )


def test_env_plan_resolved_lazily(faults, monkeypatch):
    monkeypatch.setenv("WAFFLE_FAULTS", "garbage:*:stats")
    monkeypatch.setattr(faults_mod, "_ACTIVE", None)
    monkeypatch.setattr(faults_mod, "_ENV_CHECKED", False)
    plan = faults_mod.active()
    assert plan is not None and plan.specs[0].kind == "garbage"


def test_spec_count_bounds_firings(faults):
    faults.add("timeout", count=2)
    assert faults_mod.poll("jax", "push", 0) is not None
    assert faults_mod.poll("jax", "push", 1) is not None
    assert faults_mod.poll("jax", "push", 2) is None


# -------------------------------------------------- demotion, full parity


def test_timeout_demotion_single_byte_identical(faults):
    _, expected = _run_single(_cfg())
    # two consecutive injected timeouts at dispatch 3/4 (mid-search for
    # this workload): the first attempt fails, its retry fails too ->
    # retries exhausted -> the supervisor demotes jax -> python with the
    # live handles migrated
    faults.add("timeout", backend="jax", at=3, count=None)
    faults.add("timeout", backend="jax", at=4, count=None)
    _, got = _run_single(_sup_cfg())
    demotions = events.get_events("backend_demoted")
    assert [(d["from_backend"], d["to_backend"]) for d in demotions] == [
        ("jax", "python")
    ]
    assert [(c.sequence, c.scores) for c in got] == [
        (c.sequence, c.scores) for c in expected
    ]


def test_timeout_demotion_dual_byte_identical(faults):
    _, expected = _run_dual(_cfg())
    faults.add("timeout", backend="jax", at=3, count=None)
    faults.add("timeout", backend="jax", at=4, count=None)
    _, got = _run_dual(_sup_cfg())
    assert events.get_events("backend_demoted")
    assert got == expected  # Consensus __eq__ covers sequence AND scores
    assert got[0].is_dual() == expected[0].is_dual()


def test_timeout_demotion_priority_byte_identical(faults):
    _, expected = _run_priority(_cfg())
    # unlimited timeouts on the jax backend: every supervisor the
    # priority engine constructs (one per chain level) demotes
    faults.add("timeout", backend="jax", count=None)
    _, got = _run_priority(_sup_cfg())
    assert events.get_events("backend_demoted")
    assert got == expected


def test_device_loss_demotion_single_byte_identical(faults):
    _, expected = _run_single(_cfg())
    faults.add("device_loss", backend="jax", at=3, count=None)
    faults.add("device_loss", backend="jax", at=4, count=None)
    _, got = _run_single(_sup_cfg())
    assert events.get_events("backend_demoted")
    assert [(c.sequence, c.scores) for c in got] == [
        (c.sequence, c.scores) for c in expected
    ]


# -------------------------------------------------------- retry w/o demotion


def test_transient_fault_retried_without_demotion(faults):
    _, expected = _run_single(_cfg())
    faults.add("device_loss", backend="jax", at=3, count=1)
    _, got = _run_single(_sup_cfg())
    assert len(events.get_events("dispatch_failed")) == 1
    assert events.get_events("backend_demoted") == []
    assert [(c.sequence, c.scores) for c in got] == [
        (c.sequence, c.scores) for c in expected
    ]


def test_garbage_stats_caught_by_validation(faults):
    _, expected = _run_single(_cfg())
    # the dispatch RUNS, then its BranchStats are corrupted to NaN; the
    # supervisor's validation must refuse the result and retry
    faults.add("garbage", backend="jax", op="stats", count=1)
    _, got = _run_single(_sup_cfg())
    failed = events.get_events("dispatch_failed")
    assert failed and "GarbageStats" in failed[0]["error"]
    assert [(c.sequence, c.scores) for c in got] == [
        (c.sequence, c.scores) for c in expected
    ]


def test_breaker_trips_before_retries_exhaust(faults):
    # retries would allow 5 attempts, but 2 consecutive failures trip
    # the breaker first
    faults.add("timeout", backend="jax", count=None)
    cfg = _sup_cfg(dispatch_retries=5, breaker_threshold=2)
    _run_single(cfg)
    demotions = events.get_events("backend_demoted")
    assert demotions and demotions[0]["to_backend"] == "python"
    assert len(events.get_events("dispatch_failed")) == 2


def test_chain_exhaustion_raises_backend_failure(faults):
    faults.add("timeout", count=None)  # every backend, every dispatch
    scorer = make_scorer(list(SINGLE_READS), _sup_cfg(dispatch_retries=0,
                                                      breaker_threshold=1))
    assert isinstance(scorer, BackendSupervisor)
    with pytest.raises(BackendFailure):
        scorer.root(np.ones(len(SINGLE_READS), dtype=bool))


# ------------------------------------------------------------ re-promotion


def test_repromotion_probe_returns_to_preferred_backend(faults):
    _, expected = _run_single(_cfg())
    faults.add("timeout", backend="jax", at=0, count=None)
    faults.add("timeout", backend="jax", at=1, count=None)
    _, got = _run_single(_sup_cfg(repromote_after=5))
    assert events.get_events("backend_demoted")
    promotions = events.get_events("backend_promoted")
    assert promotions and promotions[0]["to_backend"] == "jax"
    assert [(c.sequence, c.scores) for c in got] == [
        (c.sequence, c.scores) for c in expected
    ]


def test_failed_probe_backs_off_and_search_completes(faults):
    _, expected = _run_single(_cfg())
    faults.add("timeout", backend="jax", at=0, count=None)
    faults.add("timeout", backend="jax", at=1, count=None)
    # the re-promotion probe itself fails -> exponential probe backoff,
    # the search stays demoted and still finishes byte-identically
    faults.add("device_loss", backend="jax", op="probe", count=None)
    _, got = _run_single(_sup_cfg(repromote_after=3))
    assert events.get_events("probe_failed")
    assert events.get_events("backend_promoted") == []
    assert [(c.sequence, c.scores) for c in got] == [
        (c.sequence, c.scores) for c in expected
    ]


# --------------------------------------------------------------- watchdog


def test_watchdog_strict_raises_over_budget(faults):
    with pytest.raises(WatchdogError):
        _run_single(_cfg(dispatch_budget=1, watchdog_strict=True))


def test_watchdog_default_warns_over_budget(faults, caplog):
    with caplog.at_level(logging.WARNING, logger="waffle_con_tpu"):
        _, results = _run_single(_cfg(dispatch_budget=1))
    assert results  # search completed despite the violation
    assert events.get_events("watchdog_budget_exceeded")
    assert any(
        "over" in r.getMessage() and "budget" in r.getMessage()
        for r in caplog.records
    )


def test_watchdog_env_strict_mode(faults, monkeypatch):
    monkeypatch.setenv("WAFFLE_WATCHDOG", "strict")
    with pytest.raises(WatchdogError):
        _run_single(_cfg(dispatch_budget=1))


@pytest.mark.parametrize("runner", [_run_single, _run_dual, _run_priority])
def test_watchdog_passes_at_pinned_budget(faults, runner):
    # pin the budget to the workload's actual dispatch count: strict
    # mode must pass exactly at the pin (contract: > budget fails)
    engine, _ = runner(_cfg())
    pinned = dispatch_total(engine.last_search_stats["scorer_counters"])
    assert pinned > 0
    engine, _ = runner(_cfg(dispatch_budget=pinned, watchdog_strict=True))
    assert (
        dispatch_total(engine.last_search_stats["scorer_counters"]) == pinned
    )


# ------------------------------------------------- cache + pallas faults


def test_injected_cache_corruption_quarantined(faults, tmp_path, caplog):
    import jax

    from waffle_con_tpu.utils.cache import (
        QUARANTINE_DIR,
        enable_compilation_cache,
        quarantine_corrupt_entries,
    )

    cache_dir_before = jax.config.jax_compilation_cache_dir
    path = str(tmp_path / "cache")
    os.makedirs(path)
    with open(os.path.join(path, "entry_a"), "wb") as f:
        f.write(b"\x00" * 256)
    quarantine_corrupt_entries(path)  # seal into the manifest
    faults.add("cache_corrupt")
    try:
        with caplog.at_level(logging.WARNING, logger="waffle_con_tpu"):
            assert enable_compilation_cache(path) == path  # no crash
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_dir_before)
    assert events.get_events("cache_corruption_injected")
    assert events.get_events("cache_quarantine")
    # quarantined, not loadable: gone from the scan dir
    assert not os.path.exists(os.path.join(path, "entry_a"))
    assert os.path.exists(os.path.join(path, QUARANTINE_DIR, "entry_a"))
    assert any("quarantined corrupt" in r.getMessage() for r in caplog.records)


def test_pallas_compile_fault_raises_in_guard(faults):
    faults.add("pallas_compile", count=1)
    with pytest.raises(faults_mod.InjectedFault):
        faults_mod.check_pallas(1)
    faults_mod.check_pallas(1)  # count consumed: no longer armed
