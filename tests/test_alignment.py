"""Tests for the one-shot WFA edit distance (parity with the reference
doctests, ``/root/reference/src/sequence_alignment.rs:9-35``, plus DP
cross-checks)."""

import numpy as np

from waffle_con_tpu.ops.alignment import wfa_ed, wfa_ed_config
from tests.test_dwfa import dp_edit_distance


def test_doc_examples():
    v1 = bytes([0, 1, 2, 4, 5])
    v2 = bytes([0, 1, 3, 4, 5])
    v3 = bytes([1, 2, 3, 5])
    assert wfa_ed(v1, v1) == 0
    assert wfa_ed(v1, v2) == 1
    assert wfa_ed(v1, v3) == 2


def test_prefix_mode():
    v1 = bytes([0, 1, 2, 4, 5])
    v2 = bytes([0, 1, 2, 4])
    assert wfa_ed_config(v1, v2, False, ord("*")) == 0
    assert wfa_ed_config(v1, v2, True, ord("*")) == 1


def test_empty():
    assert wfa_ed_config(b"", b"", True, None) == 0
    assert wfa_ed_config(b"ABC", b"", False, None) == 0
    assert wfa_ed_config(b"ABC", b"", True, None) == 3
    assert wfa_ed_config(b"", b"ABC", True, None) == 3


def test_wildcard_either_side():
    assert wfa_ed_config(b"A*C", b"AXC", True, ord("*")) == 0
    assert wfa_ed_config(b"AXC", b"A*C", True, ord("*")) == 0


def test_random_parity_with_dp():
    rng = np.random.default_rng(11)
    for _ in range(50):
        n = int(rng.integers(0, 50))
        m = int(rng.integers(0, 50))
        a = bytes(rng.integers(0, 4, size=n, dtype=np.uint8))
        b = bytes(rng.integers(0, 4, size=m, dtype=np.uint8))
        assert wfa_ed_config(a, b, True, None) == dp_edit_distance(a, b)


def test_prefix_mode_is_min_over_prefixes():
    rng = np.random.default_rng(12)
    for _ in range(25):
        n = int(rng.integers(1, 30))
        m = int(rng.integers(1, 20))
        a = bytes(rng.integers(0, 4, size=n, dtype=np.uint8))
        b = bytes(rng.integers(0, 4, size=m, dtype=np.uint8))
        expected = min(dp_edit_distance(a[:k], b) for k in range(n + 1))
        assert wfa_ed_config(a, b, False, None) == expected
