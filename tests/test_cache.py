"""Compilation-cache integrity: fingerprint scoping + quarantine.

The cache dir is scoped by a host fingerprint precisely because loading
an entry compiled under a different jax version / XLA flag set /
platform selection can segfault inside the cache loader (utils/cache.py
docstring records two live incidents).  These tests pin the scoping and
the hash-verify/quarantine machinery without ever letting JAX load a
corrupt entry.
"""

import json
import logging
import os

import jax
import pytest

from waffle_con_tpu.utils.cache import (
    MANIFEST_NAME,
    QUARANTINE_DIR,
    _host_fingerprint,
    enable_compilation_cache,
    quarantine_corrupt_entries,
)


@pytest.fixture
def restore_cache_dir():
    """Tests below repoint the live jax compilation-cache config at tmp
    dirs; put it back so later tests keep the real persistent cache."""
    before = jax.config.jax_compilation_cache_dir
    yield
    jax.config.update("jax_compilation_cache_dir", before)


# ---------------------------------------------------------------- scoping


def test_fingerprint_changes_with_xla_flags(monkeypatch):
    base = _host_fingerprint()
    monkeypatch.setenv("XLA_FLAGS", "--xla_cpu_prefer_no_scatter=true")
    assert _host_fingerprint() != base


def test_fingerprint_changes_with_jax_version(monkeypatch):
    base = _host_fingerprint()
    monkeypatch.setattr(jax, "__version__", "0.0.0-test")
    assert _host_fingerprint() != base


def test_fingerprint_changes_with_platform_selection(monkeypatch):
    base = _host_fingerprint()
    # the conftest pins jax_platforms=cpu; a TPU-attached process resolves
    # differently and must land in a different directory
    monkeypatch.setattr(
        type(jax.config), "jax_platforms", property(lambda self: "tpu")
    )
    assert _host_fingerprint() != base


def test_distinct_fingerprints_mean_distinct_default_dirs(monkeypatch):
    dir_a = os.path.join("~", f"waffle_con_tpu_jax-{_host_fingerprint()}")
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
    dir_b = os.path.join("~", f"waffle_con_tpu_jax-{_host_fingerprint()}")
    assert dir_a != dir_b


def test_jax_cache_dir_env_override(tmp_path, restore_cache_dir, monkeypatch):
    target = str(tmp_path / "override")
    monkeypatch.setenv("JAX_CACHE_DIR", target)
    assert enable_compilation_cache() == target
    assert jax.config.jax_compilation_cache_dir == target


# ------------------------------------------------------------- quarantine


def _write_entry(path, name, data=b"\x00" * 256):
    with open(os.path.join(path, name), "wb") as f:
        f.write(data)


def test_new_entries_sealed_into_manifest(tmp_path):
    path = str(tmp_path)
    _write_entry(path, "entry_a")
    assert quarantine_corrupt_entries(path) == []
    manifest = json.load(open(os.path.join(path, MANIFEST_NAME)))
    assert "entry_a" in manifest


def test_corrupt_entry_quarantined_not_loaded(tmp_path, caplog):
    path = str(tmp_path)
    _write_entry(path, "entry_a")
    quarantine_corrupt_entries(path)  # seal
    _write_entry(path, "entry_a", b"\xff" * 256)  # corrupt in place
    with caplog.at_level(logging.WARNING, logger="waffle_con_tpu"):
        assert quarantine_corrupt_entries(path) == ["entry_a"]
    # gone from the scan dir (JAX can no longer load it), parked in
    # quarantine, dropped from the manifest
    assert not os.path.exists(os.path.join(path, "entry_a"))
    assert os.path.exists(os.path.join(path, QUARANTINE_DIR, "entry_a"))
    manifest = json.load(open(os.path.join(path, MANIFEST_NAME)))
    assert "entry_a" not in manifest
    assert any("quarantined corrupt" in r.getMessage() for r in caplog.records)


def test_intact_entries_survive_quarantine_pass(tmp_path):
    path = str(tmp_path)
    _write_entry(path, "entry_a")
    _write_entry(path, "entry_b", b"\x01" * 64)
    quarantine_corrupt_entries(path)
    _write_entry(path, "entry_a", b"\xff")  # corrupt only one
    assert quarantine_corrupt_entries(path) == ["entry_a"]
    assert os.path.exists(os.path.join(path, "entry_b"))


def test_corrupt_manifest_rebuilt(tmp_path, caplog):
    path = str(tmp_path)
    _write_entry(path, "entry_a")
    with open(os.path.join(path, MANIFEST_NAME), "w") as f:
        f.write("{not json")
    with caplog.at_level(logging.WARNING, logger="waffle_con_tpu"):
        assert quarantine_corrupt_entries(path) == []
    manifest = json.load(open(os.path.join(path, MANIFEST_NAME)))
    assert "entry_a" in manifest
    assert any("corrupt cache manifest" in r.getMessage() for r in caplog.records)


def test_vanished_entries_dropped_from_manifest(tmp_path):
    path = str(tmp_path)
    _write_entry(path, "entry_a")
    quarantine_corrupt_entries(path)
    os.remove(os.path.join(path, "entry_a"))
    quarantine_corrupt_entries(path)
    manifest = json.load(open(os.path.join(path, MANIFEST_NAME)))
    assert "entry_a" not in manifest


def test_enable_runs_quarantine(tmp_path, restore_cache_dir):
    path = str(tmp_path / "cache")
    os.makedirs(path)
    _write_entry(path, "entry_a")
    quarantine_corrupt_entries(path)
    _write_entry(path, "entry_a", b"\xff" * 8)
    assert enable_compilation_cache(path) == path
    assert os.path.exists(os.path.join(path, QUARANTINE_DIR, "entry_a"))
    assert jax.config.jax_compilation_cache_dir == path
