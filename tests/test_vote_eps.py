"""Adversarial near-tie vote stress (VERDICT r3 #6).

The device run loops (``_j_run`` / ``_j_run_dual``) continue past a
consensus position only when the f32 vote fold is provably on the same
side of every threshold as the host's f64 read-order fold — near-ties
within ``VOTE_EPS`` must bounce to host arbitration.  These tests build
datasets engineered to live near those thresholds and assert the jax
backend's *full* results (sequences, scores, assignments) equal the
Python oracle's.

Construction: a tiny repetitive alphabet with a high error rate makes
wavefront tips split (fractional votes like 1/3 that are inexact in
f32), and ``min_count`` at half the reads parks vote sums exactly on the
decision threshold.  ``corrupt`` substitutions/insertions draw from byte
values 0..3, so the alphabet is {0,1,2,3,65,66} — more candidates, more
ties.

The regression case (seed 3, unweighted) reproduces a real bug found by
this test: the dual run loop weighted unweighted votes with the
reference's 1.0/0.5/0.0 ed-comparison lattice, but the reference's
unweighted nomination uses full weight for every tracked read
(``/root/reference/src/dual_consensus.rs:1257-1262``) — the lattice is
only for ``weighted_by_ed`` (``:1299-1336``).
"""

import numpy as np
import pytest

from waffle_con_tpu import (
    CdwfaConfigBuilder,
    ConsensusDWFA,
    DualConsensusDWFA,
)
from waffle_con_tpu.config import ConsensusCost
from waffle_con_tpu.utils.example_gen import corrupt


def _single_case(seed):
    rng = np.random.default_rng(seed)
    truth = bytes(rng.choice([65, 66], size=100).tolist())
    reads = [corrupt(truth, 0.08, rng) for _ in range(10)]
    return reads


def _dual_case(seed):
    rng = np.random.default_rng(100 + seed)
    t1 = bytes(rng.choice([65, 66], size=80).tolist())
    t2 = bytearray(t1)
    t2[30] = 65 + 66 - t2[30]
    t2[60] = 65 + 66 - t2[60]
    t2 = bytes(t2)
    reads = [corrupt(t1, 0.05, rng) for _ in range(6)]
    reads += [corrupt(t2, 0.05, rng) for _ in range(6)]
    return reads


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize(
    "cost", [ConsensusCost.L1_DISTANCE, ConsensusCost.L2_DISTANCE]
)
def test_single_near_tie_parity(seed, cost):
    reads = _single_case(seed)
    results = {}
    engaged = {}
    for backend in ("python", "jax"):
        cfg = (
            CdwfaConfigBuilder()
            .min_count(5)
            .consensus_cost(cost)
            .backend(backend)
            .build()
        )
        engine = ConsensusDWFA(cfg)
        for r in reads:
            engine.add_sequence(r)
        results[backend] = engine.consensus()
        if backend == "jax":
            engaged = engine.last_search_stats["scorer_counters"]
    assert results["python"] == results["jax"]
    # a device fast path must actually run (else this test is vacuous)
    assert engaged["run_steps"] + engaged.get("arena_steps", 0) > 0


@pytest.mark.parametrize("seed", [1, 3])
@pytest.mark.parametrize("weighted", [False, True])
def test_dual_near_tie_parity(seed, weighted):
    reads = _dual_case(seed)
    results = {}
    engaged = {}
    for backend in ("python", "jax"):
        cfg = (
            CdwfaConfigBuilder()
            .min_count(3)
            .weighted_by_ed(weighted)
            .backend(backend)
            .build()
        )
        engine = DualConsensusDWFA(cfg)
        for r in reads:
            engine.add_sequence(r)
        results[backend] = engine.consensus()
        if backend == "jax":
            engaged = engine.last_search_stats["scorer_counters"]
    assert results["python"] == results["jax"]
    assert engaged["run_dual_steps"] + engaged.get("arena_steps", 0) > 0


def test_exact_threshold_split_vote():
    """Vote sums landing exactly on min_count: half the reads nominate
    each symbol, so ``maxc == min_count`` on both — a full tie the device
    must hand to the host (two passing symbols -> branch, not commit)."""
    reads = [b"AC" * 20] * 4 + [b"BC" * 20] * 4
    results = {}
    for backend in ("python", "jax"):
        cfg = CdwfaConfigBuilder().min_count(4).backend(backend).build()
        engine = ConsensusDWFA(cfg)
        for r in reads:
            engine.add_sequence(r)
        results[backend] = engine.consensus()
    assert results["python"] == results["jax"]
    # the tie produces two lexicographically ordered tied-best results
    assert len(results["jax"]) >= 1
