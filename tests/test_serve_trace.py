"""Per-job distributed tracing + flight recorder contract tests.

Core claims under test:

* In a multi-tenant serve run, every job's Chrome trace export is a
  **single connected span tree** under the job's own trace id and
  Chrome pid — worker-thread search spans and dispatcher-thread
  dispatch spans linked by parent ids, stitched across the thread hop
  by flow events.
* The span structure is **byte-identical** whether a job's dispatches
  were coalesced by the batching dispatcher or fell through the
  single-tenant direct path.
* A fault-injected job — with tracing *disabled* — still yields exactly
  one self-contained flight-recorder incident dump carrying that job's
  ring records, the runtime event log, and the SLO snapshot.
"""

import json

import pytest

from waffle_con_tpu import CdwfaConfigBuilder
from waffle_con_tpu.obs import flight, slo
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.serve import (
    ConsensusService,
    JobRequest,
    ServeConfig,
)
from waffle_con_tpu.utils.example_gen import generate_test
from waffle_con_tpu.utils.fixtures import (
    load_dual_fixture,
    load_priority_fixture,
)

pytestmark = pytest.mark.serve

DUAL_READS = (b"ACGTACGT", b"ACGTACGT", b"ACTTACGT", b"ACTTACGT")


def _cfg(**kw):
    b = CdwfaConfigBuilder().backend("python")
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return b.build()


def _mixed_requests():
    """Eight mixed-engine jobs (same shape as tests/test_serve.py)."""
    fcfg = _cfg(wildcard=ord("*"))
    requests = []
    sequences, _ = load_dual_fixture("dual_001", True, fcfg.consensus_cost)
    requests.append(
        JobRequest(kind="dual", reads=tuple(sequences), config=fcfg)
    )
    for name, include in (
        ("multi_exact_001", True),
        ("multi_err_001", False),
        ("multi_samesplit_001", True),
        ("priority_001", True),
    ):
        chains, _ = load_priority_fixture(name, include, fcfg.consensus_cost)
        requests.append(
            JobRequest(
                kind="priority",
                reads=tuple(tuple(c) for c in chains),
                config=fcfg,
                tag=name,
            )
        )
    scfg = _cfg(min_count=2)
    for seed in (0, 1):
        _, reads = generate_test(4, 160, 6, 0.02, seed=seed)
        requests.append(
            JobRequest(kind="single", reads=tuple(reads), config=scfg)
        )
    requests.append(
        JobRequest(kind="dual", reads=DUAL_READS, config=_cfg(min_count=1))
    )
    return requests


@pytest.fixture
def traced():
    """Tracing on with clean tracer/flight/SLO state, restored after."""
    tracer = obs_trace.get_tracer()
    tracer.enable(True)
    tracer.clear()
    flight.reset()
    slo.reset()
    try:
        yield tracer
    finally:
        tracer.reset_enabled()
        tracer.clear()
        flight.reset()
        slo.reset()


@pytest.fixture
def obs_clean():
    """Clean flight/SLO state with tracing left disabled."""
    flight.reset()
    slo.reset()
    try:
        yield
    finally:
        flight.reset()
        slo.reset()


# ------------------------------------------------ span-tree helpers


def _job_spans(events, trace_id):
    """The complete ``ph == "X"`` spans belonging to one trace."""
    return [
        e for e in events
        if e.get("ph") == "X"
        and e.get("args", {}).get("trace_id") == trace_id
    ]


def _span_tree(spans):
    """Normalized structure of a span set: a sorted list of root
    ``[name, children]`` shapes built from the parent links (flow
    events, timestamps, and thread ids are deliberately excluded —
    structure, not timing, must be identical across dispatch paths)."""
    children = {}
    roots = []
    for e in spans:
        parent = e["args"]["parent_id"]
        if parent is None:
            roots.append(e)
        else:
            children.setdefault(parent, []).append(e)

    def shape(e):
        kids = sorted(
            shape(c) for c in children.get(e["args"]["span_id"], [])
        )
        return [e["name"], kids]

    return sorted(shape(r) for r in roots)


# ------------------------------------------------ multi-tenant tracing


def test_every_job_gets_one_connected_span_tree(traced):
    requests = _mixed_requests()
    assert len(requests) == 8
    with ConsensusService(
        ServeConfig(workers=4, batch_window_s=0.02)
    ) as svc:
        handles = svc.submit_all(requests)
        for h in handles:
            h.result(timeout=300)
        stats = svc.stats()
    assert stats["jobs"]["done"] == len(requests)

    events = traced.chrome_events()
    for h in handles:
        trace_id = h.trace.trace_id
        spans = _job_spans(events, trace_id)
        assert spans, f"no spans recorded for {trace_id}"
        # every span carries the job's Chrome pid (its own process row)
        assert {e["pid"] for e in spans} == {h.trace.chrome_pid}, trace_id
        # parent linkage is closed: every non-root parent id exists
        ids = {e["args"]["span_id"] for e in spans}
        for e in spans:
            parent = e["args"]["parent_id"]
            assert parent is None or parent in ids, (trace_id, e)
        # one single connected tree, rooted at the job's serve:job span
        tree = _span_tree(spans)
        assert len(tree) == 1, (trace_id, [t[0] for t in tree])
        assert tree[0][0] == "serve:job"
        # the tree spans both threads' work: a search span under the
        # root and at least one dispatch span under the search
        names = {e["name"] for e in spans}
        assert "search" in names, trace_id
        assert any(n.startswith("dispatch:") for n in names), trace_id

    # jobs render as their own named Perfetto process rows
    meta_pids = {
        e["pid"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert {h.trace.chrome_pid for h in handles} <= meta_pids


def test_flow_events_stitch_worker_to_dispatcher(traced):
    """Coalesced dispatches emit paired flow start/finish events with
    matching ids, on two distinct threads of the job's pid."""
    _, reads = generate_test(4, 160, 6, 0.02, seed=0)
    requests = [
        JobRequest(
            kind="single", reads=tuple(reads), config=_cfg(min_count=2)
        )
        for _ in range(4)
    ]
    with ConsensusService(
        ServeConfig(workers=4, batch_window_s=0.02)
    ) as svc:
        handles = svc.submit_all(requests)
        for h in handles:
            h.result(timeout=300)
        stats = svc.stats()
    assert stats["dispatch"]["routed_requests"] >= 1

    events = traced.chrome_events()
    starts = {e["id"]: e for e in events if e.get("ph") == "s"}
    finishes = {e["id"]: e for e in events if e.get("ph") == "f"}
    assert starts, "no flow-start events recorded"
    paired = set(starts) & set(finishes)
    assert paired, "no flow start/finish pair with a matching id"
    job_pids = {h.trace.chrome_pid for h in handles}
    for flow_id in paired:
        s, f = starts[flow_id], finishes[flow_id]
        assert s["pid"] in job_pids and f["pid"] in job_pids
        assert s["tid"] != f["tid"], "flow did not cross threads"
        assert s["ts"] <= f["ts"]


def test_span_tree_byte_identical_coalesced_vs_direct(traced):
    _, reads = generate_test(4, 160, 6, 0.02, seed=3)

    def request():
        return JobRequest(
            kind="single", reads=tuple(reads), config=_cfg(min_count=2)
        )

    # direct fall-through: the job is alone, no batching window latency
    with ConsensusService(ServeConfig(workers=2)) as svc:
        solo = svc.submit(request())
        solo.result(timeout=300)
        solo_stats = svc.stats()
    assert solo_stats["dispatch"]["routed_requests"] == 0
    direct_tree = _span_tree(
        _job_spans(traced.chrome_events(), solo.trace.trace_id)
    )
    assert direct_tree, "no direct-path span tree"

    traced.clear()

    # coalesced: four copies of the same job race through the window
    with ConsensusService(
        ServeConfig(workers=4, batch_window_s=0.02)
    ) as svc:
        handles = svc.submit_all([request() for _ in range(4)])
        for h in handles:
            h.result(timeout=300)
        stats = svc.stats()
    assert stats["dispatch"]["routed_requests"] >= 1, (
        "nothing was routed through the dispatcher"
    )

    events = traced.chrome_events()
    direct_bytes = json.dumps(direct_tree, sort_keys=True).encode()
    for h in handles:
        tree = _span_tree(_job_spans(events, h.trace.trace_id))
        got = json.dumps(tree, sort_keys=True).encode()
        assert got == direct_bytes, (
            f"{h.trace.trace_id} span structure diverged from the "
            "single-tenant direct path"
        )


# ------------------------------------------------ flight recorder


def test_fault_injected_job_yields_exactly_one_incident_dump(
    faults, tmp_path, monkeypatch, obs_clean
):
    """Tracing stays DISABLED: the always-on flight recorder alone must
    reconstruct the demoted job's timeline in a single incident file."""
    assert not obs_trace.tracing_enabled()
    monkeypatch.setenv("WAFFLE_FLIGHT_DIR", str(tmp_path))

    def cfg(**kw):
        b = CdwfaConfigBuilder().min_count(1).backend("jax")
        for k, v in kw.items():
            b = getattr(b, k)(v)
        return b.build()

    faults.add("timeout", backend="jax", at=3, count=None)
    faults.add("timeout", backend="jax", at=4, count=None)
    sup = cfg(
        backend_chain=("python",), dispatch_retries=1,
        breaker_threshold=2, retry_backoff_s=0.0,
    )
    reads = (b"ACGTACGTACGT", b"ACGTACGTACGT", b"ACCTACGTACGT")
    with ConsensusService(ServeConfig(workers=2)) as svc:
        h = svc.submit(JobRequest(kind="single", reads=reads, config=sup))
        h.result(timeout=300)

    dumps = sorted(tmp_path.glob("incident-*.json"))
    assert len(dumps) == 1, [p.name for p in dumps]
    incident = json.loads(dumps[0].read_text())
    assert incident["schema"] == "waffle-flight-incident/1"
    assert incident["reason"] == "backend_demoted"
    assert incident["trace_id"] == h.trace.trace_id
    assert incident["detail"]["from_backend"] == "jax"
    assert incident["detail"]["to_backend"] == "python"
    # the dump is self-contained: the job's own ring records rode along
    kinds = [r["kind"] for r in incident["trace"]]
    assert "job_start" in kinds, kinds
    assert all(r["trace_id"] == h.trace.trace_id for r in incident["trace"])
    # recent runtime events and the SLO snapshot are embedded
    assert any(
        e["kind"] == "backend_demoted" for e in incident["events"]
    )
    assert "slo" in incident and "job" in incident["slo"]
    # the in-memory incident list mirrors the file (and records its path)
    mem = flight.incidents()
    assert len(mem) == 1 and mem[0]["path"] == str(dumps[0])


def test_no_anomaly_means_no_dump(tmp_path, monkeypatch, obs_clean):
    monkeypatch.setenv("WAFFLE_FLIGHT_DIR", str(tmp_path))
    _, reads = generate_test(4, 160, 6, 0.02, seed=1)
    with ConsensusService(ServeConfig(workers=2)) as svc:
        h = svc.submit(
            JobRequest(
                kind="single", reads=tuple(reads), config=_cfg(min_count=2)
            )
        )
        h.result(timeout=300)
    assert list(tmp_path.glob("*.json")) == []
    assert flight.incidents() == []


def test_deadline_exceeded_triggers_incident_without_tracing(
    tmp_path, monkeypatch, obs_clean
):
    monkeypatch.setenv("WAFFLE_FLIGHT_DIR", str(tmp_path))
    _, reads = generate_test(4, 400, 8, 0.02, seed=2)
    with ConsensusService(ServeConfig(workers=2)) as svc:
        h = svc.submit(
            JobRequest(
                kind="single", reads=tuple(reads),
                config=_cfg(min_count=2), deadline_s=1e-6,
            )
        )
        h.wait(timeout=300)
    assert h.status.value == "expired"
    dumps = sorted(tmp_path.glob("incident-*-deadline_exceeded.json"))
    assert len(dumps) == 1, [p.name for p in dumps]
    incident = json.loads(dumps[0].read_text())
    assert incident["trace_id"] == h.trace.trace_id
    assert any(
        r["kind"] == "job_start" for r in incident["trace"]
    ), incident["trace"]
