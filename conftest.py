"""Test-session setup: force JAX onto the host CPU backend with 8 virtual
devices so multi-chip sharding paths compile and execute without TPUs.

Note: this environment registers a TPU PJRT plugin from sitecustomize and
pins ``JAX_PLATFORMS`` in the ambient env, so plain env-var overrides are
ineffective — ``jax.config.update`` before first backend use is the
reliable switch (XLA_FLAGS is still read at backend init, so setting it
here works as long as no array op ran yet)."""

import os

import pytest

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Pin the speculative run-block size to 1 for the suite (tests that
# exercise speculation set WAFFLE_RUN_COLS themselves — see the spec_*
# tests in test_fuzz_parity.py; ci.sh re-runs the golden fixtures at
# K>1 and the microbench gate runs at the production default). The
# production default (K=4 on CPU) would recompile every jax test's
# kernels with a 4x-unrolled loop body, multiplying the suite's
# cold-cache compile time for zero coverage the explicit-K tests
# don't already provide.
os.environ.setdefault("WAFFLE_RUN_COLS", "1")
# Same reasoning for the megastep: the production default (on, M=8)
# would route every jax engine test through the M-block mega kernel —
# a different jit specialization per geometry than the plain path the
# rest of the suite compiles — blowing the tier-1 wall-clock budget
# for coverage tests/test_megastep.py (which sets WAFFLE_MEGASTEP
# itself, per exit class and M×K combination) already provides; ci.sh
# runs the microbench gate and bench smokes at the production default.
os.environ.setdefault("WAFFLE_MEGASTEP", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from waffle_con_tpu.utils.cache import enable_compilation_cache  # noqa: E402

enable_compilation_cache()


@pytest.fixture
def faults():
    """A fresh, installed :class:`FaultPlan`; the test adds rules via
    ``faults.add(...)``.  Teardown clears the plan AND the runtime event
    log so fault tests never leak injected state into later tests."""
    from waffle_con_tpu.runtime import events
    from waffle_con_tpu.runtime import faults as faults_mod

    plan = faults_mod.FaultPlan()
    faults_mod.install(plan)
    events.clear_events()
    try:
        yield plan
    finally:
        faults_mod.clear()
        events.clear_events()


def pytest_collection_modifyitems(config, items):
    """Deselect ``slow``-marked tests unless RUN_SLOW=1 is set or the user
    selected them explicitly with ``-m``."""
    if os.environ.get("RUN_SLOW") == "1" or config.getoption("-m"):
        return
    skip = pytest.mark.skip(reason="slow; set RUN_SLOW=1 or use -m slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
