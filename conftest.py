"""Test-session setup: force JAX onto the host CPU backend with 8 virtual
devices so multi-chip sharding paths compile and execute without TPUs.
Must run before anything imports jax."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
