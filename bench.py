#!/usr/bin/env python
"""North-star benchmark: single-consensus wall clock, TPU engine vs the
native C++ CPU engine (the reference-equivalent baseline; the reference
publishes no numbers — BASELINE.md).

Default config: 256 reads × 10 kb at 1% error (HiFi-like), alphabet 4,
min_count = reads/4 — the BASELINE.json north-star point.  Smoke mode
(``BENCH_SMOKE=1``) shrinks to 16×1000 for quick validation.

Prints exactly one JSON line:
``{"metric": ..., "value": <tpu seconds>, "unit": "s",
   "vs_baseline": <cpu_time / tpu_time>, ...}``
so ``vs_baseline`` > 1 is a speedup over the CPU baseline.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def run() -> None:
    from waffle_con_tpu import CdwfaConfigBuilder, ConsensusDWFA
    from waffle_con_tpu.native import native_consensus
    from waffle_con_tpu.utils.example_gen import generate_test

    smoke = os.environ.get("BENCH_SMOKE") == "1"
    num_reads = 16 if smoke else 256
    seq_len = 1000 if smoke else 10_000
    error_rate = 0.01
    min_count = max(2, num_reads // 4)

    gen_start = time.perf_counter()
    truth, reads = generate_test(4, seq_len, num_reads, error_rate, seed=0)
    gen_time = time.perf_counter() - gen_start

    cfg = lambda backend: (  # noqa: E731
        CdwfaConfigBuilder().min_count(min_count).backend(backend).build()
    )

    # CPU baseline: complete C++ engine
    cpu_start = time.perf_counter()
    cpu_results = native_consensus(reads, config=cfg("native"))
    cpu_time = time.perf_counter() - cpu_start

    # TPU engine: warm-up once (compile), then timed run
    def tpu_run():
        engine = ConsensusDWFA(cfg("jax"))
        for r in reads:
            engine.add_sequence(r)
        return engine.consensus()

    tpu_results = tpu_run()  # warm-up / compile
    tpu_start = time.perf_counter()
    tpu_results = tpu_run()
    tpu_time = time.perf_counter() - tpu_start

    parity = [
        (c.sequence, c.scores) for c in tpu_results
    ] == cpu_results
    recovered = tpu_results[0].sequence == truth if tpu_results else False

    print(
        json.dumps(
            {
                "metric": f"consensus_{num_reads}x{seq_len}_wall_s",
                "value": round(tpu_time, 4),
                "unit": "s",
                "vs_baseline": round(cpu_time / tpu_time, 3),
                "cpu_baseline_s": round(cpu_time, 4),
                "parity": bool(parity),
                "recovered_truth": bool(recovered),
                "gen_s": round(gen_time, 2),
            }
        )
    )


if __name__ == "__main__":
    run()
